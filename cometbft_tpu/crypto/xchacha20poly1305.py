"""XChaCha20-Poly1305 AEAD (24-byte nonces).

Reference: crypto/xchacha20poly1305/xchachapoly.go — HChaCha20 derives a
subkey from the key and the nonce's first 16 bytes, then standard
ChaCha20-Poly1305 (RFC 8439; the `cryptography` package provides the
constant-time primitive) runs with a 12-byte nonce of 4 zero bytes + the
XNonce's last 8. HChaCha20 is implemented from the draft-irtf-cfrg-xchacha
specification and checked against its published vectors."""

from __future__ import annotations

import struct

KEY_SIZE = 32
NONCE_SIZE = 24
TAG_SIZE = 16

_SIGMA = (0x61707865, 0x3320646E, 0x79622D32, 0x6B206574)
_M32 = 0xFFFFFFFF


def _rotl32(v: int, n: int) -> int:
    return ((v << n) | (v >> (32 - n))) & _M32


def _quarter(st: list[int], a: int, b: int, c: int, d: int) -> None:
    st[a] = (st[a] + st[b]) & _M32
    st[d] = _rotl32(st[d] ^ st[a], 16)
    st[c] = (st[c] + st[d]) & _M32
    st[b] = _rotl32(st[b] ^ st[c], 12)
    st[a] = (st[a] + st[b]) & _M32
    st[d] = _rotl32(st[d] ^ st[a], 8)
    st[c] = (st[c] + st[d]) & _M32
    st[b] = _rotl32(st[b] ^ st[c], 7)


def hchacha20(key: bytes, nonce16: bytes) -> bytes:
    """32-byte subkey from (32-byte key, 16-byte nonce) — 20 ChaCha rounds,
    output words 0-3 and 12-15 (no feed-forward)."""
    assert len(key) == KEY_SIZE and len(nonce16) == 16
    st = list(_SIGMA) + list(struct.unpack("<8L", key)) \
        + list(struct.unpack("<4L", nonce16))
    for _ in range(10):
        _quarter(st, 0, 4, 8, 12)
        _quarter(st, 1, 5, 9, 13)
        _quarter(st, 2, 6, 10, 14)
        _quarter(st, 3, 7, 11, 15)
        _quarter(st, 0, 5, 10, 15)
        _quarter(st, 1, 6, 11, 12)
        _quarter(st, 2, 7, 8, 13)
        _quarter(st, 3, 4, 9, 14)
    return struct.pack("<8L", *(st[i] for i in (0, 1, 2, 3, 12, 13, 14, 15)))


def _aead(key: bytes, nonce: bytes):
    try:
        from cryptography.hazmat.primitives.ciphers.aead import ChaCha20Poly1305
    except ImportError:  # degraded: pure-Python AEAD (crypto/fallback.py)
        from cometbft_tpu.crypto.fallback import ChaCha20Poly1305

    if len(key) != KEY_SIZE:
        raise ValueError("xchacha20poly1305: bad key length")
    if len(nonce) != NONCE_SIZE:
        raise ValueError("xchacha20poly1305: bad nonce length")
    subkey = hchacha20(key, nonce[:16])
    return ChaCha20Poly1305(subkey), b"\x00" * 4 + nonce[16:]


def seal(key: bytes, nonce: bytes, plaintext: bytes,
         additional_data: bytes = b"") -> bytes:
    """-> ciphertext || 16-byte tag (xchachapoly.go Seal)."""
    aead, n12 = _aead(key, nonce)
    return aead.encrypt(n12, plaintext, additional_data or None)


def open_(key: bytes, nonce: bytes, ciphertext: bytes,
          additional_data: bytes = b"") -> bytes:
    """Raises ValueError on authentication failure (xchachapoly.go Open)."""
    try:
        from cryptography.exceptions import InvalidTag
    except ImportError:
        from cometbft_tpu.crypto.fallback import InvalidTag

    aead, n12 = _aead(key, nonce)
    if len(ciphertext) < TAG_SIZE:
        raise ValueError("xchacha20poly1305: ciphertext too short")
    try:
        return aead.decrypt(n12, ciphertext, additional_data or None)
    except InvalidTag as e:
        raise ValueError("xchacha20poly1305: message authentication failed") from e
