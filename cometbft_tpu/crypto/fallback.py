"""Pure-Python stand-ins for the OpenSSL-backed primitives.

The production fast paths ride the `cryptography` package (OpenSSL). A
node must still FUNCTION without it — the same degradation philosophy as
the TPU->XLA->CPU verify ladder (ops/dispatch.py): a missing accelerator
(native crypto here, the device kernel there) costs throughput, never
liveness. Every consumer gates its import and falls back to this module:

  crypto/ed25519.py           sign/verify via the ZIP-215 oracle
  crypto/secp256k1.py         ECDSA sign (RFC 6979) / verify
  p2p/conn/secret_connection  X25519 (RFC 7748) + ChaCha20-Poly1305 (RFC 8439)
  crypto/xchacha20poly1305    the same AEAD under an HChaCha20 subkey
  crypto/xsalsa20symmetric    Poly1305

Implementations follow the RFCs directly and are cross-checked against
the reference vectors in tests/test_legacy_crypto.py / test_secp256k1.py /
test_p2p.py (which compare wire bytes with fixtures produced by the
OpenSSL-backed code paths).
"""

from __future__ import annotations

import hashlib
import hmac as _hmac
import struct

# ---------------------------------------------------------------------------
# ChaCha20 (RFC 8439 §2.3) — the quarter round lives in xchacha20poly1305
# ---------------------------------------------------------------------------

_M32 = 0xFFFFFFFF


def _chacha20_block(key: bytes, counter: int, nonce12: bytes) -> bytes:
    from cometbft_tpu.crypto.xchacha20poly1305 import _SIGMA, _quarter

    st = (list(_SIGMA) + list(struct.unpack("<8L", key)) + [counter & _M32]
          + list(struct.unpack("<3L", nonce12)))
    ws = list(st)
    for _ in range(10):
        _quarter(ws, 0, 4, 8, 12)
        _quarter(ws, 1, 5, 9, 13)
        _quarter(ws, 2, 6, 10, 14)
        _quarter(ws, 3, 7, 11, 15)
        _quarter(ws, 0, 5, 10, 15)
        _quarter(ws, 1, 6, 11, 12)
        _quarter(ws, 2, 7, 8, 13)
        _quarter(ws, 3, 4, 9, 14)
    return struct.pack("<16L", *((w + s) & _M32 for w, s in zip(ws, st)))


def _chacha20_keystream_np(key: bytes, counter: int, nonce12: bytes,
                           nblocks: int) -> bytes:
    """All `nblocks` 64-byte keystream blocks at once, quarter rounds
    vectorized over the counter axis with numpy uint32 — the p2p secret
    connection pushes every wire byte through this, so the per-byte Python
    loop of the naive version is not an option."""
    import numpy as np

    from cometbft_tpu.crypto.xchacha20poly1305 import _SIGMA

    st = np.empty((16, nblocks), dtype=np.uint32)
    st[0:4, :] = np.array(_SIGMA, dtype=np.uint32)[:, None]
    st[4:12, :] = np.frombuffer(key, dtype="<u4").astype(np.uint32)[:, None]
    st[12, :] = (np.arange(counter, counter + nblocks, dtype=np.uint64)
                 & 0xFFFFFFFF).astype(np.uint32)
    st[13:16, :] = np.frombuffer(nonce12, dtype="<u4").astype(
        np.uint32)[:, None]
    ws = st.copy()

    def rotl(v, n):
        return (v << np.uint32(n)) | (v >> np.uint32(32 - n))

    def quarter(a, b, c, d):
        ws[a] += ws[b]
        ws[d] = rotl(ws[d] ^ ws[a], 16)
        ws[c] += ws[d]
        ws[b] = rotl(ws[b] ^ ws[c], 12)
        ws[a] += ws[b]
        ws[d] = rotl(ws[d] ^ ws[a], 8)
        ws[c] += ws[d]
        ws[b] = rotl(ws[b] ^ ws[c], 7)

    with np.errstate(over="ignore"):
        for _ in range(10):
            quarter(0, 4, 8, 12)
            quarter(1, 5, 9, 13)
            quarter(2, 6, 10, 14)
            quarter(3, 7, 11, 15)
            quarter(0, 5, 10, 15)
            quarter(1, 6, 11, 12)
            quarter(2, 7, 8, 13)
            quarter(3, 4, 9, 14)
        ws += st
    # (16, N) words -> per-block little-endian byte serialization
    return ws.T.astype("<u4").tobytes()


def chacha20_xor(key: bytes, nonce12: bytes, data: bytes,
                 counter: int = 1) -> bytes:
    import numpy as np

    n = len(data)
    if n == 0:
        return b""
    nblocks = (n + 63) // 64
    stream = _chacha20_keystream_np(key, counter, nonce12, nblocks)
    buf = np.frombuffer(data, dtype=np.uint8)
    ks = np.frombuffer(stream, dtype=np.uint8)[:n]
    return (buf ^ ks).tobytes()


# ---------------------------------------------------------------------------
# Poly1305 (RFC 8439 §2.5)
# ---------------------------------------------------------------------------

_P1305 = (1 << 130) - 5
_RMASK = 0x0FFFFFFC0FFFFFFC0FFFFFFC0FFFFFFF


def poly1305_mac(key32: bytes, msg: bytes) -> bytes:
    r = int.from_bytes(key32[:16], "little") & _RMASK
    s = int.from_bytes(key32[16:32], "little")
    acc = 0
    for i in range(0, len(msg), 16):
        chunk = msg[i:i + 16]
        n = int.from_bytes(chunk, "little") + (1 << (8 * len(chunk)))
        acc = ((acc + n) * r) % _P1305
    return ((acc + s) & ((1 << 128) - 1)).to_bytes(16, "little")


class ChaCha20Poly1305:
    """RFC 8439 AEAD, API-compatible with
    cryptography.hazmat.primitives.ciphers.aead.ChaCha20Poly1305. Uses the
    process libcrypto via ctypes when present (crypto/_libcrypto.py — the
    p2p frame path is throughput-critical); pure Python otherwise."""

    def __init__(self, key: bytes):
        if len(key) != 32:
            raise ValueError("ChaCha20Poly1305 key must be 32 bytes")
        self._key = bytes(key)
        from cometbft_tpu.crypto import _libcrypto

        self._native = _libcrypto if _libcrypto.available() else None

    def _tag(self, nonce: bytes, ct: bytes, aad: bytes) -> bytes:
        poly_key = _chacha20_block(self._key, 0, nonce)[:32]
        mac_data = (aad + b"\x00" * (-len(aad) % 16)
                    + ct + b"\x00" * (-len(ct) % 16)
                    + struct.pack("<QQ", len(aad), len(ct)))
        return poly1305_mac(poly_key, mac_data)

    def encrypt(self, nonce: bytes, data: bytes, aad: bytes | None) -> bytes:
        if len(nonce) != 12:
            raise ValueError("nonce must be 12 bytes")
        aad = aad or b""
        if self._native is not None:
            return self._native.aead_seal(self._key, nonce, data, aad)
        ct = chacha20_xor(self._key, nonce, data)
        return ct + self._tag(nonce, ct, aad)

    def decrypt(self, nonce: bytes, data: bytes, aad: bytes | None) -> bytes:
        if len(nonce) != 12:
            raise ValueError("nonce must be 12 bytes")
        if len(data) < 16:
            raise InvalidTag("ciphertext too short")
        aad = aad or b""
        if self._native is not None:
            try:
                return self._native.aead_open(self._key, nonce, data, aad)
            except ValueError as e:
                raise InvalidTag(str(e)) from None
        ct, tag = data[:-16], data[-16:]
        if not _hmac.compare_digest(self._tag(nonce, ct, aad), tag):
            raise InvalidTag("poly1305 tag mismatch")
        return chacha20_xor(self._key, nonce, ct)


class InvalidTag(Exception):
    """Mirror of cryptography.exceptions.InvalidTag for gated imports."""


# ---------------------------------------------------------------------------
# X25519 (RFC 7748 §5)
# ---------------------------------------------------------------------------

_P255 = 2**255 - 19
_A24 = 121665


def _x25519_ladder(k: int, u: int) -> int:
    x1, x2, z2, x3, z3 = u, 1, 0, u, 1
    swap = 0
    for t in range(254, -1, -1):
        k_t = (k >> t) & 1
        if swap ^ k_t:
            x2, x3 = x3, x2
            z2, z3 = z3, z2
        swap = k_t
        a = (x2 + z2) % _P255
        aa = a * a % _P255
        b = (x2 - z2) % _P255
        bb = b * b % _P255
        e = (aa - bb) % _P255
        c = (x3 + z3) % _P255
        d = (x3 - z3) % _P255
        da = d * a % _P255
        cb = c * b % _P255
        x3 = (da + cb) % _P255
        x3 = x3 * x3 % _P255
        z3 = (da - cb) % _P255
        z3 = z3 * z3 % _P255
        z3 = z3 * x1 % _P255
        x2 = aa * bb % _P255
        z2 = e * (aa + _A24 * e) % _P255
    if swap:
        x2, x3 = x3, x2
        z2, z3 = z3, z2
    return x2 * pow(z2, _P255 - 2, _P255) % _P255


def x25519(scalar: bytes, u_bytes: bytes) -> bytes:
    """RFC 7748 X25519(k, u) with standard clamping. libcrypto when
    present; pure-Python Montgomery ladder otherwise."""
    from cometbft_tpu.crypto import _libcrypto

    if _libcrypto.available():
        return _libcrypto.x25519(scalar, u_bytes)
    k = int.from_bytes(scalar, "little")
    k &= ~(7 | (1 << 255))
    k |= 1 << 254
    u = int.from_bytes(u_bytes, "little") & ((1 << 255) - 1)
    out = _x25519_ladder(k, u)
    if out == 0:
        raise ValueError("x25519: low-order point (all-zero shared secret)")
    return out.to_bytes(32, "little")


X25519_BASEPOINT = (9).to_bytes(32, "little")


# ---------------------------------------------------------------------------
# secp256k1 ECDSA (SEC 2 curve, RFC 6979 deterministic nonces)
# ---------------------------------------------------------------------------

SECP_P = 0xFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFEFFFFFC2F
SECP_N = 0xFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFEBAAEDCE6AF48A03BBFD25E8CD0364141
_SECP_G = (
    0x79BE667EF9DCBBAC55A06295CE870B07029BFCDB2DCE28D959F2815B16F81798,
    0x483ADA7726A3C4655DA4FBFC0E1108A8FD17B448A68554199C47D08FFB10D4B8,
)


def _secp_add(p, q):
    if p is None:
        return q
    if q is None:
        return p
    x1, y1 = p
    x2, y2 = q
    if x1 == x2 and (y1 + y2) % SECP_P == 0:
        return None
    if p == q:
        lam = (3 * x1 * x1) * pow(2 * y1, SECP_P - 2, SECP_P) % SECP_P
    else:
        lam = (y2 - y1) * pow(x2 - x1, SECP_P - 2, SECP_P) % SECP_P
    x3 = (lam * lam - x1 - x2) % SECP_P
    return x3, (lam * (x1 - x3) - y1) % SECP_P


def _secp_mul(k: int, p):
    acc = None
    while k:
        if k & 1:
            acc = _secp_add(acc, p)
        p = _secp_add(p, p)
        k >>= 1
    return acc


def secp_point_decompress(data: bytes):
    """33-byte SEC compressed encoding -> (x, y) or None."""
    if len(data) != 33 or data[0] not in (2, 3):
        return None
    x = int.from_bytes(data[1:], "big")
    if x >= SECP_P:
        return None
    y2 = (pow(x, 3, SECP_P) + 7) % SECP_P
    y = pow(y2, (SECP_P + 1) // 4, SECP_P)
    if y * y % SECP_P != y2:
        return None
    if y & 1 != data[0] & 1:
        y = SECP_P - y
    return x, y


def secp_point_compress(p) -> bytes:
    x, y = p
    return bytes([2 | (y & 1)]) + x.to_bytes(32, "big")


def secp_pub_from_priv(d: int) -> bytes:
    return secp_point_compress(_secp_mul(d, _SECP_G))


def _rfc6979_k(d: int, h1: bytes) -> int:
    """RFC 6979 §3.2 deterministic nonce for SHA-256/secp256k1."""
    x = d.to_bytes(32, "big")
    # bits2octets: reduce the hash mod N before keying HMAC (§2.3.4)
    h1 = (int.from_bytes(h1, "big") % SECP_N).to_bytes(32, "big")
    v = b"\x01" * 32
    k = b"\x00" * 32
    k = _hmac.new(k, v + b"\x00" + x + h1, hashlib.sha256).digest()
    v = _hmac.new(k, v, hashlib.sha256).digest()
    k = _hmac.new(k, v + b"\x01" + x + h1, hashlib.sha256).digest()
    v = _hmac.new(k, v, hashlib.sha256).digest()
    while True:
        v = _hmac.new(k, v, hashlib.sha256).digest()
        cand = int.from_bytes(v, "big")
        if 0 < cand < SECP_N:
            return cand
        k = _hmac.new(k, v + b"\x00", hashlib.sha256).digest()
        v = _hmac.new(k, v, hashlib.sha256).digest()


def secp_sign(d: int, msg: bytes) -> tuple[int, int]:
    """ECDSA-SHA256 -> (r, s); caller canonicalizes S."""
    h1 = hashlib.sha256(msg).digest()
    z = int.from_bytes(h1, "big") % SECP_N
    while True:
        k = _rfc6979_k(d, h1)
        pt = _secp_mul(k, _SECP_G)
        r = pt[0] % SECP_N
        if r == 0:
            continue
        s = (z + r * d) * pow(k, SECP_N - 2, SECP_N) % SECP_N
        if s == 0:
            continue
        return r, s


def secp_verify(pub33: bytes, msg: bytes, r: int, s: int) -> bool:
    pt = secp_point_decompress(pub33)
    if pt is None or not (0 < r < SECP_N and 0 < s < SECP_N):
        return False
    z = int.from_bytes(hashlib.sha256(msg).digest(), "big") % SECP_N
    w = pow(s, SECP_N - 2, SECP_N)
    res = _secp_add(
        _secp_mul(z * w % SECP_N, _SECP_G), _secp_mul(r * w % SECP_N, pt))
    return res is not None and res[0] % SECP_N == r
