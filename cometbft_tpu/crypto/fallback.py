"""Pure-Python stand-ins for the OpenSSL-backed primitives.

The production fast paths ride the `cryptography` package (OpenSSL). A
node must still FUNCTION without it — the same degradation philosophy as
the TPU->XLA->CPU verify ladder (ops/dispatch.py): a missing accelerator
(native crypto here, the device kernel there) costs throughput, never
liveness. Every consumer gates its import and falls back to this module:

  crypto/ed25519.py           sign/verify via the ZIP-215 oracle
  crypto/secp256k1.py         ECDSA sign (RFC 6979) / verify
  p2p/conn/secret_connection  X25519 (RFC 7748) + ChaCha20-Poly1305 (RFC 8439)
  crypto/xchacha20poly1305    the same AEAD under an HChaCha20 subkey
  crypto/xsalsa20symmetric    Poly1305

Implementations follow the RFCs directly and are cross-checked against
the reference vectors in tests/test_legacy_crypto.py / test_secp256k1.py /
test_p2p.py (which compare wire bytes with fixtures produced by the
OpenSSL-backed code paths).
"""

from __future__ import annotations

import hashlib
import hmac as _hmac
import struct

# ---------------------------------------------------------------------------
# ChaCha20 (RFC 8439 §2.3) — the quarter round lives in xchacha20poly1305
# ---------------------------------------------------------------------------

_M32 = 0xFFFFFFFF


def _chacha20_block(key: bytes, counter: int, nonce12: bytes) -> bytes:
    from cometbft_tpu.crypto.xchacha20poly1305 import _SIGMA, _quarter

    st = (list(_SIGMA) + list(struct.unpack("<8L", key)) + [counter & _M32]
          + list(struct.unpack("<3L", nonce12)))
    ws = list(st)
    for _ in range(10):
        _quarter(ws, 0, 4, 8, 12)
        _quarter(ws, 1, 5, 9, 13)
        _quarter(ws, 2, 6, 10, 14)
        _quarter(ws, 3, 7, 11, 15)
        _quarter(ws, 0, 5, 10, 15)
        _quarter(ws, 1, 6, 11, 12)
        _quarter(ws, 2, 7, 8, 13)
        _quarter(ws, 3, 4, 9, 14)
    return struct.pack("<16L", *((w + s) & _M32 for w, s in zip(ws, st)))


def _chacha20_keystream_np(key: bytes, counter: int, nonce12: bytes,
                           nblocks: int) -> bytes:
    """All `nblocks` 64-byte keystream blocks at once, quarter rounds
    vectorized over the counter axis with numpy uint32 — the p2p secret
    connection pushes every wire byte through this, so the per-byte Python
    loop of the naive version is not an option."""
    import numpy as np

    from cometbft_tpu.crypto.xchacha20poly1305 import _SIGMA

    st = np.empty((16, nblocks), dtype=np.uint32)
    st[0:4, :] = np.array(_SIGMA, dtype=np.uint32)[:, None]
    st[4:12, :] = np.frombuffer(key, dtype="<u4").astype(np.uint32)[:, None]
    st[12, :] = (np.arange(counter, counter + nblocks, dtype=np.uint64)
                 & 0xFFFFFFFF).astype(np.uint32)
    st[13:16, :] = np.frombuffer(nonce12, dtype="<u4").astype(
        np.uint32)[:, None]
    ws = st.copy()

    def rotl(v, n):
        return (v << np.uint32(n)) | (v >> np.uint32(32 - n))

    def quarter(a, b, c, d):
        ws[a] += ws[b]
        ws[d] = rotl(ws[d] ^ ws[a], 16)
        ws[c] += ws[d]
        ws[b] = rotl(ws[b] ^ ws[c], 12)
        ws[a] += ws[b]
        ws[d] = rotl(ws[d] ^ ws[a], 8)
        ws[c] += ws[d]
        ws[b] = rotl(ws[b] ^ ws[c], 7)

    with np.errstate(over="ignore"):
        for _ in range(10):
            quarter(0, 4, 8, 12)
            quarter(1, 5, 9, 13)
            quarter(2, 6, 10, 14)
            quarter(3, 7, 11, 15)
            quarter(0, 5, 10, 15)
            quarter(1, 6, 11, 12)
            quarter(2, 7, 8, 13)
            quarter(3, 4, 9, 14)
        ws += st
    # (16, N) words -> per-block little-endian byte serialization
    return ws.T.astype("<u4").tobytes()


def chacha20_xor(key: bytes, nonce12: bytes, data: bytes,
                 counter: int = 1) -> bytes:
    import numpy as np

    n = len(data)
    if n == 0:
        return b""
    nblocks = (n + 63) // 64
    stream = _chacha20_keystream_np(key, counter, nonce12, nblocks)
    buf = np.frombuffer(data, dtype=np.uint8)
    ks = np.frombuffer(stream, dtype=np.uint8)[:n]
    return (buf ^ ks).tobytes()


# ---------------------------------------------------------------------------
# Poly1305 (RFC 8439 §2.5)
# ---------------------------------------------------------------------------

_P1305 = (1 << 130) - 5
_RMASK = 0x0FFFFFFC0FFFFFFC0FFFFFFC0FFFFFFF


def poly1305_mac(key32: bytes, msg: bytes) -> bytes:
    r = int.from_bytes(key32[:16], "little") & _RMASK
    s = int.from_bytes(key32[16:32], "little")
    acc = 0
    for i in range(0, len(msg), 16):
        chunk = msg[i:i + 16]
        n = int.from_bytes(chunk, "little") + (1 << (8 * len(chunk)))
        acc = ((acc + n) * r) % _P1305
    return ((acc + s) & ((1 << 128) - 1)).to_bytes(16, "little")


class ChaCha20Poly1305:
    """RFC 8439 AEAD, API-compatible with
    cryptography.hazmat.primitives.ciphers.aead.ChaCha20Poly1305. Uses the
    process libcrypto via ctypes when present (crypto/_libcrypto.py — the
    p2p frame path is throughput-critical); pure Python otherwise."""

    def __init__(self, key: bytes):
        if len(key) != 32:
            raise ValueError("ChaCha20Poly1305 key must be 32 bytes")
        self._key = bytes(key)
        from cometbft_tpu.crypto import _libcrypto

        self._native = _libcrypto if _libcrypto.available() else None

    def _tag(self, nonce: bytes, ct: bytes, aad: bytes) -> bytes:
        poly_key = _chacha20_block(self._key, 0, nonce)[:32]
        mac_data = (aad + b"\x00" * (-len(aad) % 16)
                    + ct + b"\x00" * (-len(ct) % 16)
                    + struct.pack("<QQ", len(aad), len(ct)))
        return poly1305_mac(poly_key, mac_data)

    def encrypt(self, nonce: bytes, data: bytes, aad: bytes | None) -> bytes:
        if len(nonce) != 12:
            raise ValueError("nonce must be 12 bytes")
        aad = aad or b""
        if self._native is not None:
            return self._native.aead_seal(self._key, nonce, data, aad)
        ct = chacha20_xor(self._key, nonce, data)
        return ct + self._tag(nonce, ct, aad)

    def decrypt(self, nonce: bytes, data: bytes, aad: bytes | None) -> bytes:
        if len(nonce) != 12:
            raise ValueError("nonce must be 12 bytes")
        if len(data) < 16:
            raise InvalidTag("ciphertext too short")
        aad = aad or b""
        if self._native is not None:
            try:
                return self._native.aead_open(self._key, nonce, data, aad)
            except ValueError as e:
                raise InvalidTag(str(e)) from None
        ct, tag = data[:-16], data[-16:]
        if not _hmac.compare_digest(self._tag(nonce, ct, aad), tag):
            raise InvalidTag("poly1305 tag mismatch")
        return chacha20_xor(self._key, nonce, ct)


class InvalidTag(Exception):
    """Mirror of cryptography.exceptions.InvalidTag for gated imports."""


# ---------------------------------------------------------------------------
# X25519 (RFC 7748 §5)
# ---------------------------------------------------------------------------

_P255 = 2**255 - 19
_A24 = 121665


def _x25519_ladder(k: int, u: int) -> int:
    x1, x2, z2, x3, z3 = u, 1, 0, u, 1
    swap = 0
    for t in range(254, -1, -1):
        k_t = (k >> t) & 1
        if swap ^ k_t:
            x2, x3 = x3, x2
            z2, z3 = z3, z2
        swap = k_t
        a = (x2 + z2) % _P255
        aa = a * a % _P255
        b = (x2 - z2) % _P255
        bb = b * b % _P255
        e = (aa - bb) % _P255
        c = (x3 + z3) % _P255
        d = (x3 - z3) % _P255
        da = d * a % _P255
        cb = c * b % _P255
        x3 = (da + cb) % _P255
        x3 = x3 * x3 % _P255
        z3 = (da - cb) % _P255
        z3 = z3 * z3 % _P255
        z3 = z3 * x1 % _P255
        x2 = aa * bb % _P255
        z2 = e * (aa + _A24 * e) % _P255
    if swap:
        x2, x3 = x3, x2
        z2, z3 = z3, z2
    return x2 * pow(z2, _P255 - 2, _P255) % _P255


def x25519(scalar: bytes, u_bytes: bytes) -> bytes:
    """RFC 7748 X25519(k, u) with standard clamping. libcrypto when
    present; pure-Python Montgomery ladder otherwise."""
    from cometbft_tpu.crypto import _libcrypto

    if _libcrypto.available():
        return _libcrypto.x25519(scalar, u_bytes)
    k = int.from_bytes(scalar, "little")
    k &= ~(7 | (1 << 255))
    k |= 1 << 254
    u = int.from_bytes(u_bytes, "little") & ((1 << 255) - 1)
    out = _x25519_ladder(k, u)
    if out == 0:
        raise ValueError("x25519: low-order point (all-zero shared secret)")
    return out.to_bytes(32, "little")


X25519_BASEPOINT = (9).to_bytes(32, "little")


# ---------------------------------------------------------------------------
# secp256k1 ECDSA (SEC 2 curve, RFC 6979 deterministic nonces)
# ---------------------------------------------------------------------------

SECP_P = 0xFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFEFFFFFC2F
SECP_N = 0xFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFEBAAEDCE6AF48A03BBFD25E8CD0364141
_SECP_G = (
    0x79BE667EF9DCBBAC55A06295CE870B07029BFCDB2DCE28D959F2815B16F81798,
    0x483ADA7726A3C4655DA4FBFC0E1108A8FD17B448A68554199C47D08FFB10D4B8,
)


def _secp_add(p, q):
    if p is None:
        return q
    if q is None:
        return p
    x1, y1 = p
    x2, y2 = q
    if x1 == x2 and (y1 + y2) % SECP_P == 0:
        return None
    if p == q:
        lam = (3 * x1 * x1) * pow(2 * y1, SECP_P - 2, SECP_P) % SECP_P
    else:
        lam = (y2 - y1) * pow(x2 - x1, SECP_P - 2, SECP_P) % SECP_P
    x3 = (lam * lam - x1 - x2) % SECP_P
    return x3, (lam * (x1 - x3) - y1) % SECP_P


def _secp_mul(k: int, p):
    acc = None
    while k:
        if k & 1:
            acc = _secp_add(acc, p)
        p = _secp_add(p, p)
        k >>= 1
    return acc


def secp_point_decompress(data: bytes):
    """33-byte SEC compressed encoding -> (x, y) or None."""
    if len(data) != 33 or data[0] not in (2, 3):
        return None
    x = int.from_bytes(data[1:], "big")
    if x >= SECP_P:
        return None
    y2 = (pow(x, 3, SECP_P) + 7) % SECP_P
    y = pow(y2, (SECP_P + 1) // 4, SECP_P)
    if y * y % SECP_P != y2:
        return None
    if y & 1 != data[0] & 1:
        y = SECP_P - y
    return x, y


def secp_point_compress(p) -> bytes:
    x, y = p
    return bytes([2 | (y & 1)]) + x.to_bytes(32, "big")


def secp_pub_from_priv(d: int) -> bytes:
    return secp_point_compress(_secp_mul(d, _SECP_G))


def _rfc6979_k(d: int, h1: bytes) -> int:
    """RFC 6979 §3.2 deterministic nonce for SHA-256/secp256k1."""
    x = d.to_bytes(32, "big")
    # bits2octets: reduce the hash mod N before keying HMAC (§2.3.4)
    h1 = (int.from_bytes(h1, "big") % SECP_N).to_bytes(32, "big")
    v = b"\x01" * 32
    k = b"\x00" * 32
    k = _hmac.new(k, v + b"\x00" + x + h1, hashlib.sha256).digest()
    v = _hmac.new(k, v, hashlib.sha256).digest()
    k = _hmac.new(k, v + b"\x01" + x + h1, hashlib.sha256).digest()
    v = _hmac.new(k, v, hashlib.sha256).digest()
    while True:
        v = _hmac.new(k, v, hashlib.sha256).digest()
        cand = int.from_bytes(v, "big")
        if 0 < cand < SECP_N:
            return cand
        k = _hmac.new(k, v + b"\x00", hashlib.sha256).digest()
        v = _hmac.new(k, v, hashlib.sha256).digest()


def secp_sign(d: int, msg: bytes) -> tuple[int, int]:
    """ECDSA-SHA256 -> (r, s); caller canonicalizes S."""
    h1 = hashlib.sha256(msg).digest()
    z = int.from_bytes(h1, "big") % SECP_N
    while True:
        k = _rfc6979_k(d, h1)
        pt = _secp_mul(k, _SECP_G)
        r = pt[0] % SECP_N
        if r == 0:
            continue
        s = (z + r * d) * pow(k, SECP_N - 2, SECP_N) % SECP_N
        if s == 0:
            continue
        return r, s


def secp_verify(pub33: bytes, msg: bytes, r: int, s: int) -> bool:
    pt = secp_point_decompress(pub33)
    if pt is None or not (0 < r < SECP_N and 0 < s < SECP_N):
        return False
    z = int.from_bytes(hashlib.sha256(msg).digest(), "big") % SECP_N
    w = pow(s, SECP_N - 2, SECP_N)
    res = _secp_add(
        _secp_mul(z * w % SECP_N, _SECP_G), _secp_mul(r * w % SECP_N, pt))
    return res is not None and res[0] % SECP_N == r


# ---------------------------------------------------------------------------
# BLS12-381 (min-pubkey-size: 48 B G1 pubkeys, 96 B G2 signatures) — the
# exact CPU oracle behind crypto/bls12381.py and the correctness reference
# for the vectorized device path (ops/bls12381/, ops/bls_kernel.py).
#
# Everything here is pure-Python integer arithmetic; nothing below touches
# numpy or jax. Domain knowledge is kept SELF-CALIBRATING where the spec
# needs big derived constants: the curve parameters are tied together by
# integer identities asserted at import (r = x^4 - x^2 + 1,
# 3p = (x-1)^2 r + 3x), the G2 cofactor comes from the sextic-twist order
# computed out of the Frobenius trace (and is checked by killing mapped
# points), the SvdW hash-to-curve Z and the final-exponentiation addition
# chain both validate themselves before use. Hash-to-curve follows the
# draft-irtf-cfrg-hash-to-curve pipeline (expand_message_xmd/SHA-256 ->
# hash_to_field -> map -> clear_cofactor) with the GENERIC
# Shallue-van de Woestijne map of RFC 9380 §6.6.1 — the registered G2
# ciphersuite's 3-isogeny SSWU constants are deliberately not reproduced
# from memory, so the suite is draft-structured but carries its own DST
# (bls12381.DST). The aggregation semantics are the proof-of-possession
# flavor: validators in a consensus validator set are registered keys, so
# identical sign-bytes across signers aggregate (FastAggregateVerify-
# style) instead of being rejected for non-distinctness.
# ---------------------------------------------------------------------------

BLS_P = 0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFAAAB
BLS_R = 0x73EDA753299D7D483339D80809A1D80553BDA402FFFE5BFEFFFFFFFF00000001
BLS_X = -0xD201000000010000  # the BLS12-381 curve parameter (negative)

# parameter cross-checks: the family polynomials tie p, r and x together —
# a typo in any one of the three fails here at import, not in a test
assert BLS_R == BLS_X**4 - BLS_X**2 + 1, "BLS12-381 r/x mismatch"
assert 3 * BLS_P == (BLS_X - 1) ** 2 * BLS_R + 3 * BLS_X, "BLS12-381 p/x mismatch"

# generators (standard encodings' affine coordinates); both are checked
# against their curve equations at import
BLS_G1 = (
    0x17F1D3A73197D7942695638C4FA9AC0FC3688C4F9774B905A14E3A3F171BAC586C55E83FF97A1AEFFB3AF00ADB22C6BB,
    0x08B3F481E3AAA0F1A09E30ED741D8AE4FCF5E095D5D00AF600DB18CB2C04B3EDD03CC744A2888AE40CAA232946C5E7E1,
)
BLS_G2 = (
    (
        0x024AA2B2F08F0A91260805272DC51051C6E47AD4FA403B02B4510B647AE3D1770BAC0326A805BBEFD48056C8C121BDB8,
        0x13E02B6052719F607DACD3A088274F65596BD0D09920B61AB5DA61BBDC7F5049334CF11213945D57E5AC7D055D042B7E,
    ),
    (
        0x0CE5D527727D6E118CC9CDC6DA2E351AADFD9BAA8CBDD3A76D429A695160D12C923AC9CC3BACA289E193548608B82801,
        0x0606C4A02EA734CC32ACD2B02BC28B99CB3E287E85A763AF267492AB572E99AB3F370D275CEC1DA1AAA9075FF05F79BE,
    ),
)

_P = BLS_P


def _f1_add(a, b):
    return (a + b) % _P


def _f1_sub(a, b):
    return (a - b) % _P


def _f1_mul(a, b):
    return a * b % _P


def _f1_sq(a):
    return a * a % _P


def _f1_neg(a):
    return -a % _P


def _f1_inv(a):
    return pow(a, _P - 2, _P)


assert _f1_sq(BLS_G1[1]) == (BLS_G1[0] ** 3 + 4) % _P, "G1 generator off-curve"

# ---- Fp2 = Fp[u] / (u^2 + 1); elements are (a0, a1) = a0 + a1*u --------

F2_ZERO = (0, 0)
F2_ONE = (1, 0)
BLS_XI = (1, 1)  # the Fp6/Fp12 tower non-residue xi = 1 + u


def f2_add(a, b):
    return ((a[0] + b[0]) % _P, (a[1] + b[1]) % _P)


def f2_sub(a, b):
    return ((a[0] - b[0]) % _P, (a[1] - b[1]) % _P)


def f2_neg(a):
    return (-a[0] % _P, -a[1] % _P)


def f2_mul(a, b):
    a0, a1 = a
    b0, b1 = b
    t0 = a0 * b0
    t1 = a1 * b1
    t2 = (a0 + a1) * (b0 + b1)
    return ((t0 - t1) % _P, (t2 - t0 - t1) % _P)


def f2_sq(a):
    a0, a1 = a
    return ((a0 + a1) * (a0 - a1) % _P, 2 * a0 * a1 % _P)


def f2_conj(a):
    return (a[0], -a[1] % _P)


def f2_inv(a):
    n = pow((a[0] * a[0] + a[1] * a[1]) % _P, _P - 2, _P)
    return (a[0] * n % _P, -a[1] * n % _P)


def f2_mul_fp(a, k):
    return (a[0] * k % _P, a[1] * k % _P)


def f2_mul_xi(a):
    # (1 + u)(a0 + a1 u) = (a0 - a1) + (a0 + a1) u
    return ((a[0] - a[1]) % _P, (a[0] + a[1]) % _P)


def f2_pow(a, e):
    out = F2_ONE
    while e:
        if e & 1:
            out = f2_mul(out, a)
        a = f2_sq(a)
        e >>= 1
    return out


def f2_is_zero(a):
    return a[0] % _P == 0 and a[1] % _P == 0


def f2_legendre_is_square(a):
    """a is a square in Fp2 iff norm(a)^((p-1)/2) == 1 (or a == 0):
    a^((p^2-1)/2) = (a^(p+1))^((p-1)/2) = norm(a)^((p-1)/2)."""
    if f2_is_zero(a):
        return True
    n = (a[0] * a[0] + a[1] * a[1]) % _P
    return pow(n, (_P - 1) // 2, _P) == 1


def f2_sqrt(a):
    """Square root in Fp2 for p = 3 mod 4 (alg. 9, eprint 2012/685);
    returns None when a is not a square."""
    if f2_is_zero(a):
        return F2_ZERO
    a1 = f2_pow(a, (_P - 3) // 4)
    alpha = f2_mul(f2_sq(a1), a)
    x0 = f2_mul(a1, a)
    if alpha == (_P - 1, 0):
        x = f2_mul((0, 1), x0)
    else:
        b = f2_pow(f2_add(F2_ONE, alpha), (_P - 1) // 2)
        x = f2_mul(b, x0)
    return x if f2_sq(x) == (a[0] % _P, a[1] % _P) else None


def f2_sgn0(a):
    """RFC 9380 sgn0 for m = 2."""
    s0 = a[0] % 2
    z0 = a[0] % _P == 0
    return s0 | (z0 and (a[1] % 2))


_B2 = f2_mul_fp(BLS_XI, 4)  # the twist constant: E'/Fp2: y^2 = x^3 + 4(1+u)
assert f2_sq(BLS_G2[1]) == f2_add(f2_mul(f2_sq(BLS_G2[0]), BLS_G2[0]), _B2), \
    "G2 generator off-curve"


# ---- Fp6 = Fp2[v] / (v^3 - xi); elements (c0, c1, c2) ------------------

F6_ZERO = (F2_ZERO, F2_ZERO, F2_ZERO)
F6_ONE = (F2_ONE, F2_ZERO, F2_ZERO)


def f6_add(a, b):
    return (f2_add(a[0], b[0]), f2_add(a[1], b[1]), f2_add(a[2], b[2]))


def f6_sub(a, b):
    return (f2_sub(a[0], b[0]), f2_sub(a[1], b[1]), f2_sub(a[2], b[2]))


def f6_neg(a):
    return (f2_neg(a[0]), f2_neg(a[1]), f2_neg(a[2]))


def f6_mul(a, b):
    a0, a1, a2 = a
    b0, b1, b2 = b
    t0, t1, t2 = f2_mul(a0, b0), f2_mul(a1, b1), f2_mul(a2, b2)
    c0 = f2_add(t0, f2_mul_xi(f2_sub(
        f2_mul(f2_add(a1, a2), f2_add(b1, b2)), f2_add(t1, t2))))
    c1 = f2_add(f2_sub(f2_mul(f2_add(a0, a1), f2_add(b0, b1)),
                       f2_add(t0, t1)), f2_mul_xi(t2))
    c2 = f2_add(f2_sub(f2_mul(f2_add(a0, a2), f2_add(b0, b2)),
                       f2_add(t0, t2)), t1)
    return (c0, c1, c2)


def f6_sq(a):
    return f6_mul(a, a)


def f6_mul_v(a):
    """v * (c0 + c1 v + c2 v^2) = xi*c2 + c0 v + c1 v^2."""
    return (f2_mul_xi(a[2]), a[0], a[1])


def f6_inv(a):
    a0, a1, a2 = a
    c0 = f2_sub(f2_sq(a0), f2_mul_xi(f2_mul(a1, a2)))
    c1 = f2_sub(f2_mul_xi(f2_sq(a2)), f2_mul(a0, a1))
    c2 = f2_sub(f2_sq(a1), f2_mul(a0, a2))
    t = f2_inv(f2_add(f2_mul(a0, c0),
                      f2_mul_xi(f2_add(f2_mul(a2, c1), f2_mul(a1, c2)))))
    return (f2_mul(c0, t), f2_mul(c1, t), f2_mul(c2, t))


# ---- Fp12 = Fp6[w] / (w^2 - v); elements (d0, d1) ----------------------

F12_ONE = (F6_ONE, F6_ZERO)


def f12_mul(a, b):
    t0 = f6_mul(a[0], b[0])
    t1 = f6_mul(a[1], b[1])
    d1 = f6_sub(f6_sub(
        f6_mul(f6_add(a[0], a[1]), f6_add(b[0], b[1])), t0), t1)
    return (f6_add(t0, f6_mul_v(t1)), d1)


def f12_sq(a):
    return f12_mul(a, a)


def f12_conj(a):
    return (a[0], f6_neg(a[1]))


def f12_inv(a):
    t = f6_inv(f6_sub(f6_sq(a[0]), f6_mul_v(f6_sq(a[1]))))
    return (f6_mul(a[0], t), f6_neg(f6_mul(a[1], t)))


def f12_pow(a, e):
    if e < 0:
        return f12_pow(f12_inv(a), -e)
    out = F12_ONE
    while e:
        if e & 1:
            out = f12_mul(out, a)
        a = f12_sq(a)
        e >>= 1
    return out


# Frobenius: (v^i w^j)^(p^n) = v^i w^j * xi^((p^n - 1)(2i + j)/6) with the
# Fp2 coefficients taken to the p^n power (conjugated when n is odd). The
# twelve gamma constants are COMPUTED, not transcribed.
_FROB_G1 = [f2_pow(BLS_XI, k * (_P - 1) // 6) for k in range(6)]


def f12_frob(a, n=1):
    """a^(p^n) for n in (1, 2, 3, ...): apply the p-power map n times."""
    for _ in range(n):
        d0 = tuple(f2_mul(f2_conj(a[0][i]), _FROB_G1[2 * i])
                   for i in range(3))
        d1 = tuple(f2_mul(f2_conj(a[1][i]), _FROB_G1[2 * i + 1])
                   for i in range(3))
        a = (d0, d1)
    return a


# ---- Jacobian point arithmetic over a generic field --------------------
# point = None (infinity) or (X, Y, Z); curve y^2 = x^3 + b, a = 0.

class _FpOps:
    add = staticmethod(_f1_add)
    sub = staticmethod(_f1_sub)
    mul = staticmethod(_f1_mul)
    sq = staticmethod(_f1_sq)
    neg = staticmethod(_f1_neg)
    inv = staticmethod(_f1_inv)
    is_zero = staticmethod(lambda a: a % _P == 0)
    ONE = 1
    B = 4


class _Fp2Ops:
    add = staticmethod(f2_add)
    sub = staticmethod(f2_sub)
    mul = staticmethod(f2_mul)
    sq = staticmethod(f2_sq)
    neg = staticmethod(f2_neg)
    inv = staticmethod(f2_inv)
    is_zero = staticmethod(f2_is_zero)
    ONE = F2_ONE
    B = _B2


def _ec_dbl(F, p):
    if p is None or F.is_zero(p[1]):
        return None
    X, Y, Z = p
    A = F.sq(X)
    B = F.sq(Y)
    C = F.sq(B)
    D = F.sub(F.sub(F.sq(F.add(X, B)), A), C)
    D = F.add(D, D)
    E = F.add(F.add(A, A), A)
    Fv = F.sq(E)
    X3 = F.sub(Fv, F.add(D, D))
    Y3 = F.sub(F.mul(E, F.sub(D, X3)), F.add(F.add(F.add(C, C), F.add(C, C)),
                                             F.add(F.add(C, C), F.add(C, C))))
    Z3 = F.add(F.mul(Y, Z), F.mul(Y, Z))
    return (X3, Y3, Z3)


def _ec_add(F, p, q):
    if p is None:
        return q
    if q is None:
        return p
    X1, Y1, Z1 = p
    X2, Y2, Z2 = q
    Z1Z1 = F.sq(Z1)
    Z2Z2 = F.sq(Z2)
    U1 = F.mul(X1, Z2Z2)
    U2 = F.mul(X2, Z1Z1)
    S1 = F.mul(F.mul(Y1, Z2), Z2Z2)
    S2 = F.mul(F.mul(Y2, Z1), Z1Z1)
    if F.is_zero(F.sub(U1, U2)):
        if F.is_zero(F.sub(S1, S2)):
            return _ec_dbl(F, p)
        return None
    H = F.sub(U2, U1)
    I = F.sq(F.add(H, H))
    J = F.mul(H, I)
    r = F.add(F.sub(S2, S1), F.sub(S2, S1))
    V = F.mul(U1, I)
    X3 = F.sub(F.sub(F.sq(r), J), F.add(V, V))
    S1J = F.mul(S1, J)
    Y3 = F.sub(F.mul(r, F.sub(V, X3)), F.add(S1J, S1J))
    Z3 = F.mul(F.mul(H, Z1), Z2)
    Z3 = F.add(Z3, Z3)
    return (X3, Y3, Z3)


def _ec_mul(F, k, p):
    out = None
    if k < 0:
        k, p = -k, _ec_neg(p)
    while k:
        if k & 1:
            out = _ec_add(F, out, p)
        p = _ec_dbl(F, p)
        k >>= 1
    return out


def _ec_neg(p):
    if p is None:
        return None
    return (p[0], tuple((-c) % _P for c in p[1]) if isinstance(p[1], tuple)
            else (-p[1]) % _P, p[2])


def _ec_affine(F, p):
    if p is None:
        return None
    zi = F.inv(p[2])
    zi2 = F.sq(zi)
    return (F.mul(p[0], zi2), F.mul(p[1], F.mul(zi, zi2)))


def _ec_from_affine(a):
    if a is None:
        return None
    one = F2_ONE if isinstance(a[0], tuple) else 1
    return (a[0], a[1], one)


def _ec_on_curve(F, a):
    """Affine (x, y) on y^2 = x^3 + F.B (infinity counts as on-curve)."""
    if a is None:
        return True
    return F.is_zero(F.sub(F.sq(a[1]), F.add(F.mul(F.sq(a[0]), a[0]), F.B)))


# ---- optimal ate pairing ------------------------------------------------
#
# The Miller variable T walks E'(Fp2) (the sextic twist) in affine form;
# line values are mapped into Fp12 through the untwist
# (x', y') -> (x'/w^2, y'/w^3) with w^6 = xi, which lands the evaluated
# line at P = (xP, yP) in three sparse slots:
#
#   l(P) = yP  +  ((lam*x0 - y0) * xi^-1) * (v w)  +  (-lam*xP * xi^-1) * (v^2 w)
#
# where lam is the twist-coordinate slope and (x0, y0) a twist point on the
# line. Any Fp2 scaling of a line value is killed by the final
# exponentiation (the (p^6 - 1) factor), which is what makes the affine
# normalization here and the projective normalization in ops/bls12381
# interchangeable — the tests assert the two pipelines agree bit-for-bit.

_XI_INV = f2_inv(BLS_XI)


def _line_f12(lam, x0, y0, xP, yP):
    """The sparse evaluated line as a full Fp12 element."""
    c_vw = f2_mul(f2_sub(f2_mul(lam, x0), y0), _XI_INV)
    c_v2w = f2_mul(f2_mul_fp(lam, xP), _XI_INV)
    c_v2w = f2_neg(c_v2w)
    return (((yP % _P, 0), F2_ZERO, F2_ZERO), (F2_ZERO, c_vw, c_v2w))


def bls_miller_loop(p_aff, q_aff):
    """f_{|x|,Q}(P) conjugated for the negative x — one Miller loop.
    p_aff: affine G1 (x, y) ints; q_aff: affine G2 ((..), (..)) Fp2 pairs.
    Either argument None (infinity) gives the neutral 1 (the pairing with
    infinity is degenerate; callers reject infinity points upstream)."""
    if p_aff is None or q_aff is None:
        return F12_ONE
    xP, yP = p_aff
    xQ, yQ = q_aff
    f = F12_ONE
    tx, ty = xQ, yQ
    bits = bin(-BLS_X)[2:]
    for bit in bits[1:]:
        # tangent at T
        lam = f2_mul(f2_mul_fp(f2_sq(tx), 3), f2_inv(f2_add(ty, ty)))
        f = f12_mul(f12_sq(f), _line_f12(lam, tx, ty, xP, yP))
        # T = 2T (affine)
        x2 = f2_sub(f2_sq(lam), f2_add(tx, tx))
        ty = f2_sub(f2_mul(lam, f2_sub(tx, x2)), ty)
        tx = x2
        if bit == "1":
            # chord through T and Q
            lam = f2_mul(f2_sub(ty, yQ), f2_inv(f2_sub(tx, xQ)))
            f = f12_mul(f, _line_f12(lam, tx, ty, xP, yP))
            x2 = f2_sub(f2_sub(f2_sq(lam), tx), xQ)
            ty = f2_sub(f2_mul(lam, f2_sub(tx, x2)), ty)
            tx = x2
    return f12_conj(f)  # x < 0


# hard-part addition chain: (x-1)^2 (x+p) (x^2+p^2-1) + 3 computes
# 3*(p^4-p^2+1)/r — a cubed pairing, still a non-degenerate bilinear map
# (3 does not divide r). Verified here; if the identity ever failed the
# plain-exponent fallback below keeps the oracle correct.
_HARD_CHAIN_OK = (
    (BLS_X - 1) ** 2 * (BLS_X + BLS_P)
    * (BLS_X**2 + BLS_P**2 - 1) + 3
    == 3 * (BLS_P**4 - BLS_P**2 + 1) // BLS_R
)
assert (BLS_P**4 - BLS_P**2 + 1) % BLS_R == 0


def _cyclo_exp(a, e):
    """a^e for a in the cyclotomic subgroup (a^(p^6+1-ish) structure from
    the easy part): inverse is conjugation, so negative e is cheap."""
    if e < 0:
        return _cyclo_exp(f12_conj(a), -e)
    out = F12_ONE
    while e:
        if e & 1:
            out = f12_mul(out, a)
        a = f12_sq(a)
        e >>= 1
    return out


def bls_final_exp(f):
    """f^((p^12 - 1)/r) (times a harmless cube when the addition chain is
    active — both sides of every pairing comparison use the same map)."""
    # easy part: f^((p^6 - 1)(p^2 + 1))
    f = f12_mul(f12_conj(f), f12_inv(f))
    f = f12_mul(f12_frob(f, 2), f)
    if not _HARD_CHAIN_OK:  # pragma: no cover - guarded self-calibration
        return _cyclo_exp(f, (BLS_P**4 - BLS_P**2 + 1) // BLS_R)
    # hard part: f^((x-1)^2 (x+p) (x^2+p^2-1) + 3)
    y = _cyclo_exp(_cyclo_exp(f, BLS_X - 1), BLS_X - 1)
    y = f12_mul(_cyclo_exp(y, BLS_X), f12_frob(y, 1))
    y2 = _cyclo_exp(_cyclo_exp(y, BLS_X), BLS_X)
    y = f12_mul(f12_mul(y2, f12_frob(y, 2)), f12_conj(y))
    return f12_mul(y, f12_mul(f12_sq(f), f))


def bls_pairing(p_aff, q_aff):
    """e(P, Q) for affine P in E(Fp), Q in E'(Fp2)."""
    return bls_final_exp(bls_miller_loop(p_aff, q_aff))


def bls_pairing_product_is_one(pairs) -> bool:
    """prod e(P_i, Q_i) == 1 with ONE shared final exponentiation — the
    aggregate-verify core."""
    f = F12_ONE
    for p_aff, q_aff in pairs:
        f = f12_mul(f, bls_miller_loop(p_aff, q_aff))
    return bls_final_exp(f) == F12_ONE


# ---- hash-to-curve (draft-irtf-cfrg-hash-to-curve pipeline) ------------


def bls_expand_message_xmd(msg: bytes, dst: bytes, len_in_bytes: int) -> bytes:
    """expand_message_xmd with SHA-256 (RFC 9380 §5.3.1), exactly as
    specified — checked against the RFC's reference vectors in
    tests/test_bls.py. Batch call sites route through
    ops/hashvec.sha256_many for rung accounting."""
    if len(dst) > 255:
        dst = hashlib.sha256(b"H2C-OVERSIZE-DST-" + dst).digest()
    ell = -(-len_in_bytes // 32)
    if ell > 255 or len_in_bytes > 65535:
        raise ValueError("expand_message_xmd length out of range")
    dst_prime = dst + bytes([len(dst)])
    z_pad = bytes(64)  # SHA-256 block size
    l_i_b = len_in_bytes.to_bytes(2, "big")
    b0 = hashlib.sha256(z_pad + msg + l_i_b + b"\x00" + dst_prime).digest()
    out = []
    bi = hashlib.sha256(b0 + b"\x01" + dst_prime).digest()
    out.append(bi)
    for i in range(2, ell + 1):
        bi = hashlib.sha256(
            bytes(x ^ y for x, y in zip(b0, bi)) + bytes([i]) + dst_prime
        ).digest()
        out.append(bi)
    return b"".join(out)[:len_in_bytes]


_H2F_L = 64  # ceil((ceil(log2(p)) + k) / 8) for 128-bit security margin


def bls_hash_to_field_fp2(msg: bytes, dst: bytes, count: int = 2):
    """hash_to_field for Fp2 (m = 2, L = 64): `count` field elements."""
    uniform = bls_expand_message_xmd(msg, dst, count * 2 * _H2F_L)
    out = []
    for i in range(count):
        comps = []
        for j in range(2):
            off = _H2F_L * (j + i * 2)
            comps.append(int.from_bytes(uniform[off:off + _H2F_L], "big") % _P)
        out.append(tuple(comps))
    return out


def _svdw_setup_fp2():
    """Find and validate the SvdW constants for E'/Fp2 (RFC 9380 §6.6.1
    with A = 0, B = 4(1+u)). Z is searched, not transcribed; the returned
    constants are validated by mapping a few field elements and checking
    the curve equation, so a bad candidate can never install."""
    def g(x):
        return f2_add(f2_mul(f2_sq(x), x), _B2)

    three = (3, 0)
    four_inv = pow(4, _P - 2, _P)
    for cand in ((0, 1), (1, 0), (1, 1), (_P - 1, 0), (0, _P - 1),
                 (2, 0), (_P - 2, 0), (2, 1), (1, 2), (3, 0), (_P - 3, 0)):
        z = cand
        gz = g(z)
        if f2_is_zero(gz):
            continue
        h = f2_mul(three, f2_sq(z))  # 3Z^2 + 4A, A = 0
        if f2_is_zero(h):
            continue
        # -(3Z^2 + 4A) / (4 g(Z)) must be square and nonzero
        crit = f2_mul(f2_neg(h), f2_mul_fp(f2_inv(gz), four_inv))
        if f2_is_zero(crit) or not f2_legendre_is_square(crit):
            continue
        neg_z_half = f2_mul_fp(f2_neg(z), (_P + 1) // 2)
        if not (f2_legendre_is_square(gz)
                or f2_legendre_is_square(g(neg_z_half))):
            continue
        c3 = f2_sqrt(f2_mul(f2_neg(gz), h))
        if c3 is None:
            continue
        if f2_sgn0(c3) == 1:
            c3 = f2_neg(c3)
        c4 = f2_mul(f2_mul_fp(f2_neg(gz), 4), f2_inv(h))
        consts = (z, gz, neg_z_half, c3, c4)
        # self-validation: the map must land on the curve
        if all(_ec_on_curve(_Fp2Ops, _svdw_map_fp2(u, consts))
               for u in (F2_ZERO, F2_ONE, (5, 7), (1234567, 7654321))):
            return consts
    raise RuntimeError("no SvdW Z parameter found for the BLS12-381 twist")


def _svdw_map_fp2(u, consts):
    """map_to_curve_svdw (RFC 9380 §6.6.1) on E'/Fp2."""
    z, c1, c2, c3, c4 = consts

    def g(x):
        return f2_add(f2_mul(f2_sq(x), x), _B2)

    tv1 = f2_mul(f2_sq(u), c1)
    tv2 = f2_add(F2_ONE, tv1)
    tv1 = f2_sub(F2_ONE, tv1)
    tv3 = f2_mul(tv1, tv2)
    tv3 = f2_inv(tv3) if not f2_is_zero(tv3) else F2_ZERO  # inv0
    tv4 = f2_mul(f2_mul(u, tv1), f2_mul(tv3, c3))
    x1 = f2_sub(c2, tv4)
    x2 = f2_add(c2, tv4)
    x3 = f2_add(f2_mul(f2_sq(f2_mul(f2_sq(tv2), tv3)), c4), z)
    if f2_legendre_is_square(g(x1)):
        x = x1
    elif f2_legendre_is_square(g(x2)):
        x = x2
    else:
        x = x3
    y = f2_sqrt(g(x))
    if y is None:  # cannot happen with valid constants
        raise RuntimeError("SvdW: g(x) not square")
    if f2_sgn0(u) != f2_sgn0(y):
        y = f2_neg(y)
    return (x, y)


_bls_lazy: dict = {}


def _bls_setup():
    """Lazy derived constants: SvdW map constants and the G2 cofactor
    (computed from the sextic-twist order, then verified by killing
    mapped points — never transcribed)."""
    if _bls_lazy:
        return _bls_lazy
    t = BLS_X + 1  # Frobenius trace of E/Fp
    assert BLS_P + 1 - t == ((BLS_X - 1) ** 2 // 3) * BLS_R
    fsq, rem = divmod(4 * BLS_P - t * t, 3)
    assert rem == 0
    fint = _isqrt(fsq)
    assert fint * fint == fsq, "BLS trace discriminant not -3*f^2"
    t2 = t * t - 2 * BLS_P  # trace over Fp2
    f2_ = t * fint
    n = None
    for cand in (BLS_P**2 + 1 - (t2 + 3 * f2_) // 2,
                 BLS_P**2 + 1 - (t2 - 3 * f2_) // 2):
        if cand % BLS_R == 0:
            n = cand
            break
    assert n is not None, "no sextic twist order divisible by r"
    svdw = _svdw_setup_fp2()
    # verify the order: it must kill arbitrary points of E'(Fp2)
    for u in ((7, 11), (13, 17)):
        pt = _ec_from_affine(_svdw_map_fp2(u, svdw))
        assert _ec_mul(_Fp2Ops, n, pt) is None, "twist order FAILED"
    _bls_lazy.update({
        "svdw": svdw,
        "h2": n // BLS_R,
        "h1": (BLS_X - 1) ** 2 // 3,
    })
    return _bls_lazy


def _isqrt(n: int) -> int:
    import math

    return math.isqrt(n)


def bls_hash_to_g2(msg: bytes, dst: bytes):
    """hash_to_curve for G2: hash_to_field (2 elements) -> SvdW map each ->
    point add -> clear cofactor. Returns an affine Fp2 pair in the r-order
    subgroup (never infinity for any realistic input; infinity would be
    rejected by the signer/verifier path anyway)."""
    setup = _bls_setup()
    u0, u1 = bls_hash_to_field_fp2(msg, dst, 2)
    q0 = _ec_from_affine(_svdw_map_fp2(u0, setup["svdw"]))
    q1 = _ec_from_affine(_svdw_map_fp2(u1, setup["svdw"]))
    pt = _ec_mul(_Fp2Ops, setup["h2"], _ec_add(_Fp2Ops, q0, q1))
    return _ec_affine(_Fp2Ops, pt)


# ---- serialization (ZCash-style compressed encodings) ------------------

_F_COMPRESSED = 0x80
_F_INFINITY = 0x40
_F_SIGN = 0x20


def _y_is_lexi_larger(y) -> bool:
    if isinstance(y, tuple):
        if y[1] % _P != 0:
            return y[1] % _P > (_P - 1) // 2
        return y[0] % _P > (_P - 1) // 2
    return y % _P > (_P - 1) // 2


def bls_g1_compress(aff) -> bytes:
    if aff is None:
        return bytes([_F_COMPRESSED | _F_INFINITY]) + bytes(47)
    x, y = aff
    flags = _F_COMPRESSED | (_F_SIGN if _y_is_lexi_larger(y) else 0)
    out = bytearray(x.to_bytes(48, "big"))
    out[0] |= flags
    return bytes(out)


def bls_g1_decompress(data: bytes):
    """48-byte compressed G1 -> affine (x, y) | None (infinity) — raises
    ValueError on structural garbage (flags, x >= p, not on curve)."""
    if len(data) != 48:
        raise ValueError("bls12381 G1 point must be 48 bytes")
    flags = data[0]
    if not flags & _F_COMPRESSED:
        raise ValueError("uncompressed G1 encoding not supported")
    body = bytes([data[0] & 0x1F]) + data[1:]
    if flags & _F_INFINITY:
        if any(body) or flags & _F_SIGN:
            raise ValueError("malformed G1 infinity encoding")
        return None
    x = int.from_bytes(body, "big")
    if x >= _P:
        raise ValueError("G1 x out of range")
    yy = (x * x % _P * x + 4) % _P
    y = pow(yy, (_P + 1) // 4, _P)
    if y * y % _P != yy:
        raise ValueError("G1 x not on curve")
    if bool(flags & _F_SIGN) != _y_is_lexi_larger(y):
        y = _P - y
    return (x, y)


def bls_g2_compress(aff) -> bytes:
    if aff is None:
        return bytes([_F_COMPRESSED | _F_INFINITY]) + bytes(95)
    (x0, x1), y = aff
    flags = _F_COMPRESSED | (_F_SIGN if _y_is_lexi_larger(y) else 0)
    out = bytearray(x1.to_bytes(48, "big") + x0.to_bytes(48, "big"))
    out[0] |= flags
    return bytes(out)


def bls_g2_decompress(data: bytes):
    """96-byte compressed G2 (x_c1 || x_c0) -> affine pair | None."""
    if len(data) != 96:
        raise ValueError("bls12381 G2 point must be 96 bytes")
    flags = data[0]
    if not flags & _F_COMPRESSED:
        raise ValueError("uncompressed G2 encoding not supported")
    body = bytes([data[0] & 0x1F]) + data[1:]
    if flags & _F_INFINITY:
        if any(body) or flags & _F_SIGN:
            raise ValueError("malformed G2 infinity encoding")
        return None
    x1 = int.from_bytes(body[:48], "big")
    x0 = int.from_bytes(body[48:], "big")
    if x0 >= _P or x1 >= _P:
        raise ValueError("G2 x out of range")
    x = (x0, x1)
    y = f2_sqrt(f2_add(f2_mul(f2_sq(x), x), _B2))
    if y is None:
        raise ValueError("G2 x not on curve")
    if bool(flags & _F_SIGN) != _y_is_lexi_larger(y):
        y = f2_neg(y)
    return (x, y)


# ---- the signature scheme (min-pubkey-size, PoP-style aggregation) -----


def bls_pub_from_priv(sk: int) -> bytes:
    return bls_g1_compress(
        _ec_affine(_FpOps, _ec_mul(_FpOps, sk % BLS_R, _ec_from_affine(BLS_G1))))


def bls_pubkey_validate(pub: bytes) -> bool:
    """KeyValidate: decodes, rejects infinity (the zero/identity pubkey
    forges any aggregate) and points outside the r-order subgroup."""
    try:
        aff = bls_g1_decompress(pub)
    except ValueError:
        return False
    if aff is None:
        return False
    return _ec_mul(_FpOps, BLS_R, _ec_from_affine(aff)) is None


def bls_signature_validate(sig: bytes):
    """Decode + validate a G2 signature point: subgroup-checked, infinity
    rejected. Returns the affine point or None when invalid."""
    try:
        aff = bls_g2_decompress(sig)
    except ValueError:
        return None
    if aff is None:
        return None
    if _ec_mul(_Fp2Ops, BLS_R, _ec_from_affine(aff)) is not None:
        return None
    return aff


def bls_sign(sk: int, msg: bytes, dst: bytes) -> bytes:
    h = bls_hash_to_g2(msg, dst)
    return bls_g2_compress(
        _ec_affine(_Fp2Ops, _ec_mul(_Fp2Ops, sk % BLS_R, _ec_from_affine(h))))


_NEG_G1 = (BLS_G1[0], _P - BLS_G1[1])


def bls_verify(pub: bytes, msg: bytes, sig: bytes, dst: bytes) -> bool:
    """CoreVerify: e(g1, sig) == e(pk, H(msg)) via one pairing product."""
    if not bls_pubkey_validate(pub):
        return False
    sig_aff = bls_signature_validate(sig)
    if sig_aff is None:
        return False
    pk_aff = bls_g1_decompress(pub)
    h = bls_hash_to_g2(msg, dst)
    return bls_pairing_product_is_one([(_NEG_G1, sig_aff), (pk_aff, h)])


def bls_aggregate(sigs) -> bytes:
    """Sum the signature points. Raises ValueError when any input fails
    to DECODE (off-curve, non-canonical, malformed flags) or is the
    infinity point. Per-signature SUBGROUP checks are deliberately not
    repeated here: the aggregate itself is subgroup-checked by
    bls_aggregate_verify (which is what the pairing equation constrains
    — only the SUM enters it), and individual subgroup membership is
    enforced where signatures are admitted one at a time (bls_verify /
    the batched single-verify path). This is what keeps commit
    aggregation O(n) cheap point adds instead of n scalar-mul subgroup
    scans."""
    acc = None
    for s in sigs:
        try:
            aff = bls_g2_decompress(bytes(s))
        except ValueError:
            aff = None
        if aff is None:
            raise ValueError("bls12381 aggregate: invalid signature input")
        acc = _ec_add(_Fp2Ops, acc, _ec_from_affine(aff))
    if acc is None:
        raise ValueError("bls12381 aggregate: empty input")
    return bls_g2_compress(_ec_affine(_Fp2Ops, acc))


def bls_aggregate_verify(pubs, msgs, sig: bytes, dst: bytes) -> bool:
    """PoP-flavor AggregateVerify: messages may repeat (same-sign-bytes
    votes aggregate their pubkeys), every pubkey must KeyValidate, the
    aggregate signature must be a subgroup point and not infinity. One
    pairing-product check with a single final exponentiation:

        e(g1, sig) == prod over distinct m of e(sum pk_i[m_i == m], H(m))
    """
    if len(pubs) != len(msgs) or not pubs:
        return False
    sig_aff = bls_signature_validate(sig)
    if sig_aff is None:
        return False
    groups: dict[bytes, list] = {}
    for pub, msg in zip(pubs, msgs):
        if not bls_pubkey_validate(bytes(pub)):
            return False
        groups.setdefault(bytes(msg), []).append(bls_g1_decompress(bytes(pub)))
    pairs = [(_NEG_G1, sig_aff)]
    for msg, pk_affs in groups.items():
        acc = None
        for aff in pk_affs:
            acc = _ec_add(_FpOps, acc, _ec_from_affine(aff))
        if acc is None:  # pragma: no cover - groups are never empty
            return False
        pk_sum = _ec_affine(_FpOps, acc)
        if pk_sum is None:
            # pubkeys in one message group cancelled to infinity: the
            # group contributes nothing and the check degenerates —
            # reject loudly rather than accept a forgeable shape
            return False
        pairs.append((pk_sum, bls_hash_to_g2(msg, dst)))
    return bls_pairing_product_is_one(pairs)
