"""Pure-Python Ed25519 group arithmetic with ZIP-215 verification semantics.

This is the framework's *semantic oracle*: the reference verifies votes with
curve25519-voi under ZIP-215 rules (reference: crypto/ed25519/ed25519.go:37-42
— cofactored equation, non-canonical point encodings accepted, S < L
enforced). The TPU kernel (ops/ed25519_kernel.py) must agree with this module
bit-for-bit on every input; tests drive both against each other and against
RFC 8032 vectors.

Not a production verify path — Python bignums are ~ms per verification. The
production paths are the OpenSSL-backed single verify (crypto/ed25519.py) and
the JAX/TPU batch kernel.
"""

from __future__ import annotations

import hashlib
import secrets

# ---------------------------------------------------------------- field

P = 2**255 - 19
L = 2**252 + 27742317777372353535851937790883648493
D = (-121665 * pow(121666, P - 2, P)) % P
D2 = (2 * D) % P
SQRT_M1 = pow(2, (P - 1) // 4, P)  # sqrt(-1)

# Base point: y = 4/5, x recovered with even sign.
_BY = (4 * pow(5, P - 2, P)) % P


def _recover_x(y: int, sign: int) -> int | None:
    """RFC 8032 §5.1.3 x-recovery. Returns None if no square root exists or
    if x == 0 with sign == 1."""
    u = (y * y - 1) % P
    v = (D * y * y + 1) % P
    # candidate = (u/v)^((p+3)/8) = u * v^3 * (u*v^7)^((p-5)/8)
    x = (u * pow(v, 3, P) * pow(u * pow(v, 7, P) % P, (P - 5) // 8, P)) % P
    vxx = (v * x * x) % P
    if vxx == u:
        pass
    elif vxx == (-u) % P:
        x = (x * SQRT_M1) % P
    else:
        return None
    if x == 0 and sign == 1:
        return None
    if x & 1 != sign:
        x = P - x
    return x


BX = _recover_x(_BY, 0)
assert BX is not None

# Extended homogeneous coordinates (X : Y : Z : T), x = X/Z, y = Y/Z, T = XY/Z.
Point = tuple[int, int, int, int]

IDENTITY: Point = (0, 1, 1, 0)
B_POINT: Point = (BX, _BY, 1, (BX * _BY) % P)


def point_add(p1: Point, p2: Point) -> Point:
    """Complete unified addition, add-2008-hwcd-3 for a=-1 (branch-free —
    the same formula the lockstep TPU lanes use)."""
    X1, Y1, Z1, T1 = p1
    X2, Y2, Z2, T2 = p2
    a = (Y1 - X1) * (Y2 - X2) % P
    b = (Y1 + X1) * (Y2 + X2) % P
    c = T1 * D2 % P * T2 % P
    d = 2 * Z1 * Z2 % P
    e, f, g, h = b - a, d - c, d + c, b + a
    return (e * f % P, g * h % P, f * g % P, e * h % P)


def point_double(p1: Point) -> Point:
    """dbl-2008-hwcd."""
    X1, Y1, Z1, _ = p1
    a = X1 * X1 % P
    b = Y1 * Y1 % P
    c = 2 * Z1 * Z1 % P
    h = (a + b) % P
    e = (h - (X1 + Y1) * (X1 + Y1)) % P
    g = (a - b) % P
    f = (c + g) % P
    return (e * f % P, g * h % P, f * g % P, e * h % P)


def point_neg(p1: Point) -> Point:
    X, Y, Z, T = p1
    return ((-X) % P, Y, Z, (-T) % P)


def scalar_mult(k: int, p1: Point) -> Point:
    """Double-and-add, MSB first."""
    acc = IDENTITY
    for i in reversed(range(k.bit_length())):
        acc = point_double(acc)
        if (k >> i) & 1:
            acc = point_add(acc, p1)
    return acc


def double_scalar_mult(k1: int, p1: Point, k2: int, p2: Point) -> Point:
    """[k1]p1 + [k2]p2, interleaved (Straus) — mirrors the TPU kernel's joint
    scan shape with the 4-entry table {O, p1, p2, p1+p2}."""
    table = (IDENTITY, p1, p2, point_add(p1, p2))
    acc = IDENTITY
    for i in reversed(range(max(k1.bit_length(), k2.bit_length(), 1))):
        acc = point_double(acc)
        idx = ((k1 >> i) & 1) | (((k2 >> i) & 1) << 1)
        if idx:
            acc = point_add(acc, table[idx])
    return acc


def point_equal(p1: Point, p2: Point) -> bool:
    X1, Y1, Z1, _ = p1
    X2, Y2, Z2, _ = p2
    return (X1 * Z2 - X2 * Z1) % P == 0 and (Y1 * Z2 - Y2 * Z1) % P == 0


def is_identity(p1: Point) -> bool:
    X, Y, Z, _ = p1
    return X % P == 0 and (Y - Z) % P == 0


def point_compress(p1: Point) -> bytes:
    X, Y, Z, _ = p1
    zi = pow(Z, P - 2, P)
    x = X * zi % P
    y = Y * zi % P
    return (y | ((x & 1) << 255)).to_bytes(32, "little")


def point_decompress_zip215(data: bytes) -> Point | None:
    """ZIP-215 decompression: the y candidate is NOT required to be canonical
    (y >= p accepted, reduced mod p); x-recovery per RFC 8032 otherwise.
    Matches curve25519-voi's VerifyOptionsZIP_215 behavior that the reference
    selects (crypto/ed25519/ed25519.go:37-42)."""
    if len(data) != 32:
        return None
    enc = int.from_bytes(data, "little")
    sign = enc >> 255
    y = (enc & ((1 << 255) - 1)) % P  # non-canonical accepted: reduce
    x = _recover_x(y, sign)
    if x is None:
        return None
    return (x, y, 1, x * y % P)


def point_decompress_canonical(data: bytes) -> Point | None:
    """Strict RFC 8032 decompression (rejects non-canonical y) — used for
    our own key material and signing."""
    if len(data) != 32:
        return None
    enc = int.from_bytes(data, "little")
    sign = enc >> 255
    y = enc & ((1 << 255) - 1)
    if y >= P:
        return None
    x = _recover_x(y, sign)
    if x is None:
        return None
    return (x, y, 1, x * y % P)


def mul_by_cofactor(p1: Point) -> Point:
    return point_double(point_double(point_double(p1)))


# ---------------------------------------------------------------- scheme


def _sha512_mod_l(*parts: bytes) -> int:
    h = hashlib.sha512()
    for part in parts:
        h.update(part)
    return int.from_bytes(h.digest(), "little") % L


def secret_expand(seed: bytes) -> tuple[int, bytes]:
    h = hashlib.sha512(seed).digest()
    a = int.from_bytes(h[:32], "little")
    a &= (1 << 254) - 8
    a |= 1 << 254
    return a, h[32:]


def public_key_from_seed(seed: bytes) -> bytes:
    a, _ = secret_expand(seed)
    return point_compress(scalar_mult(a, B_POINT))


def sign(seed: bytes, msg: bytes) -> bytes:
    """RFC 8032 signing (oracle/testing; production signing uses OpenSSL)."""
    a, prefix = secret_expand(seed)
    pub = point_compress(scalar_mult(a, B_POINT))
    r = int.from_bytes(hashlib.sha512(prefix + msg).digest(), "little") % L
    R = point_compress(scalar_mult(r, B_POINT))
    k = _sha512_mod_l(R, pub, msg)
    s = (r + k * a) % L
    return R + s.to_bytes(32, "little")


def verify_zip215(pub: bytes, msg: bytes, sig: bytes) -> bool:
    """ZIP-215 single verification: cofactored [8][S]B == [8]R + [8][k]A with
    non-canonical A/R accepted and S < L enforced."""
    if len(sig) != 64 or len(pub) != 32:
        return False
    A = point_decompress_zip215(pub)
    if A is None:
        return False
    R = point_decompress_zip215(sig[:32])
    if R is None:
        return False
    s = int.from_bytes(sig[32:], "little")
    if s >= L:
        return False
    k = _sha512_mod_l(sig[:32], pub, msg)
    # [S]B - [k]A - R, then clear cofactor: identity iff valid.
    sb_ka = double_scalar_mult(s, B_POINT, k, point_neg(A))
    diff = point_add(sb_ka, point_neg(R))
    return is_identity(mul_by_cofactor(diff))


def batch_verify_zip215(pubs: list[bytes], msgs: list[bytes],
                        sigs: list[bytes]) -> tuple[bool, list[bool]]:
    """Random-linear-combination batch verification, ZIP-215 semantics
    (reference: crypto/ed25519/ed25519.go:208-241). On failure, falls back to
    per-signature verification to produce the validity mask — exactly the
    reference's verifyCommitBatch → verifyCommitSingle fallback shape
    (types/validation.go:235,266)."""
    n = len(sigs)
    assert len(pubs) == n and len(msgs) == n
    if n == 0:
        return True, []
    # Stage: decompress + range-check; any malformed input fails fast to
    # the per-sig path so the mask pinpoints it.
    items = []
    ok_shapes = True
    for pub, msg, sig in zip(pubs, msgs, sigs):
        if len(sig) != 64 or len(pub) != 32:
            ok_shapes = False
            break
        A = point_decompress_zip215(pub)
        R = point_decompress_zip215(sig[:32])
        s = int.from_bytes(sig[32:], "little")
        if A is None or R is None or s >= L:
            ok_shapes = False
            break
        items.append((A, R, s, _sha512_mod_l(sig[:32], pub, msg)))
    if ok_shapes:
        # sum_i z_i * (s_i B - R_i - k_i A_i) == 0 (cofactored)
        zs = [1] + [secrets.randbits(128) | 1 for _ in range(n - 1)]
        s_acc = 0
        acc = IDENTITY
        for (A, R, s, k), z in zip(items, zs):
            s_acc = (s_acc + z * s) % L
            acc = point_add(acc, scalar_mult(z % L, R))
            acc = point_add(acc, scalar_mult(z * k % L, A))
        check = point_add(scalar_mult(s_acc, B_POINT), point_neg(acc))
        if is_identity(mul_by_cofactor(check)):
            return True, [True] * n
    mask = [verify_zip215(pub, msg, sig)
            for pub, msg, sig in zip(pubs, msgs, sigs)]
    return all(mask), mask
