"""RFC-6962 merkle trees (reference: crypto/merkle/tree.go:11-101, proof.go).

Used for block-part sets, tx roots, validator-set hashes, header hashes.
Leaf hash = SHA256(0x00 || leaf); inner = SHA256(0x01 || left || right);
split point = largest power of two strictly less than n; empty tree hash =
SHA256("").
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass, field

_LEAF_PREFIX = b"\x00"
_INNER_PREFIX = b"\x01"


def _sha256(data: bytes) -> bytes:
    return hashlib.sha256(data).digest()


def empty_hash() -> bytes:
    return _sha256(b"")


def leaf_hash(leaf: bytes) -> bytes:
    return _sha256(_LEAF_PREFIX + leaf)


def inner_hash(left: bytes, right: bytes) -> bytes:
    return _sha256(_INNER_PREFIX + left + right)


def get_split_point(n: int) -> int:
    """Largest power of 2 strictly less than n (reference tree.go:93-101)."""
    if n < 2:
        raise ValueError("n must be >= 2")
    return 1 << ((n - 1).bit_length() - 1)


def hash_from_byte_slices(items: list[bytes]) -> bytes:
    n = len(items)
    if n == 0:
        return empty_hash()
    if n == 1:
        return leaf_hash(items[0])
    k = get_split_point(n)
    return inner_hash(hash_from_byte_slices(items[:k]), hash_from_byte_slices(items[k:]))


@dataclass
class Proof:
    """Merkle inclusion proof (reference: crypto/merkle/proof.go:20-33)."""

    total: int
    index: int
    leaf_hash: bytes
    aunts: list[bytes] = field(default_factory=list)

    def verify(self, root_hash: bytes, leaf: bytes) -> bool:
        if self.total < 0 or self.index < 0:
            return False
        if leaf_hash(leaf) != self.leaf_hash:
            return False
        computed = self.compute_root_hash()
        return computed is not None and computed == root_hash

    def compute_root_hash(self) -> bytes | None:
        return _compute_hash_from_aunts(self.index, self.total, self.leaf_hash, self.aunts)


def _compute_hash_from_aunts(index: int, total: int, leaf: bytes,
                             aunts: list[bytes]) -> bytes | None:
    """reference: crypto/merkle/proof.go:161-191."""
    if index >= total or index < 0 or total <= 0:
        return None
    if total == 1:
        if aunts:
            return None
        return leaf
    if not aunts:
        return None
    k = get_split_point(total)
    if index < k:
        left = _compute_hash_from_aunts(index, k, leaf, aunts[:-1])
        if left is None:
            return None
        return inner_hash(left, aunts[-1])
    right = _compute_hash_from_aunts(index - k, total - k, leaf, aunts[:-1])
    if right is None:
        return None
    return inner_hash(aunts[-1], right)


def proofs_from_byte_slices(items: list[bytes]) -> tuple[bytes, list[Proof]]:
    """Root hash + one proof per item (reference: proof.go:61-78)."""
    trails, root = _trails_from_byte_slices(items)
    root_hash = root.hash
    proofs = []
    for i, trail in enumerate(trails):
        proofs.append(Proof(total=len(items), index=i, leaf_hash=trail.hash,
                            aunts=trail.flatten_aunts()))
    return root_hash, proofs


class _ProofNode:
    __slots__ = ("hash", "parent", "left", "right")

    def __init__(self, h: bytes):
        self.hash = h
        self.parent: _ProofNode | None = None
        self.left: _ProofNode | None = None   # left sibling (aunt)
        self.right: _ProofNode | None = None  # right sibling (aunt)

    def flatten_aunts(self) -> list[bytes]:
        aunts: list[bytes] = []
        node: _ProofNode | None = self
        while node is not None:
            if node.left is not None:
                aunts.append(node.left.hash)
            elif node.right is not None:
                aunts.append(node.right.hash)
            node = node.parent
        return aunts


def _trails_from_byte_slices(items: list[bytes]) -> tuple[list[_ProofNode], _ProofNode]:
    n = len(items)
    if n == 0:
        return [], _ProofNode(empty_hash())
    if n == 1:
        node = _ProofNode(leaf_hash(items[0]))
        return [node], node
    k = get_split_point(n)
    lefts, left_root = _trails_from_byte_slices(items[:k])
    rights, right_root = _trails_from_byte_slices(items[k:])
    root = _ProofNode(inner_hash(left_root.hash, right_root.hash))
    left_root.parent = root
    left_root.right = right_root
    right_root.parent = root
    right_root.left = left_root
    return lefts + rights, root
