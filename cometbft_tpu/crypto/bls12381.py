"""BLS12-381 keys (min-pubkey-size variant: 48 B G1 pubkeys, 96 B G2
signatures) — the third verify-plane scheme.

Motivation (PAPERS.md, "Performance of EdDSA and BLS Signatures in
Committee-Based Consensus"): BLS aggregation makes commit size and verify
cost ~independent of committee size — a mega-commit decides with ONE
pairing-product check instead of one lane-verify per validator
(types/validation.py wires the aggregate path; ops/bls_kernel.py carries
the batched single-verify path through the scheduler/mesh like ed25519
and sr25519).

Signing and the exact CPU verification oracle live in crypto/fallback.py
(pure-Python pairing, self-calibrating derived constants). Aggregation
semantics are the proof-of-possession flavor: validator sets are
registered keys, so identical sign-bytes across signers aggregate their
pubkeys (FastAggregateVerify-style) instead of being rejected for
non-distinctness. The hash-to-curve suite follows the
draft-irtf-cfrg-hash-to-curve pipeline with the generic SvdW map and
therefore carries its own DST (see fallback.py for why the registered
ciphersuite's 3-isogeny constants are not reproduced here).

Enablement: the scheme registers with crypto/batch only when
`crypto.bls_enabled` is on (the default). With it off, a BLS key
reaching the batch seam raises a LOUD ErrInvalidKey naming the knob —
misconfiguration must never silently fall back (the light-proxy https
refusal rule).
"""

from __future__ import annotations

import hashlib
import secrets

from cometbft_tpu import crypto
from cometbft_tpu.crypto import fallback as _bls
from cometbft_tpu.crypto import tmhash

KEY_TYPE = "bls12381"
PUB_KEY_SIZE = 48
PRIV_KEY_SIZE = 32
SIGNATURE_SIZE = 96

# Domain separation tag. The suite string is honest about the map in use:
# the pipeline is draft-structured (expand_message_xmd/SHA-256 ->
# hash_to_field -> map -> clear_cofactor) with the generic SvdW map of
# RFC 9380 §6.6.1 rather than the registered G2 SSWU isogeny suite.
DST = b"BLS_SIG_BLS12381G2_XMD:SHA-256_SVDW_RO_CBFT_"

_enabled = True


def set_enabled(on: bool) -> None:
    """Applied from config.crypto.bls_enabled at node boot
    (crypto/batch.configure)."""
    global _enabled
    _enabled = bool(on)


def enabled() -> bool:
    return _enabled


class PubKey(crypto.PubKey):
    __slots__ = ("_bytes", "_valid")

    def __init__(self, data: bytes):
        if len(data) != PUB_KEY_SIZE:
            raise crypto.ErrInvalidKey(
                f"bls12381 pubkey must be {PUB_KEY_SIZE} bytes")
        self._bytes = bytes(data)
        self._valid: bool | None = None  # KeyValidate result, lazy

    def address(self) -> bytes:
        return tmhash.sum_truncated(self._bytes)

    def bytes_(self) -> bytes:
        return self._bytes

    def type_(self) -> str:
        return KEY_TYPE

    def key_validate(self) -> bool:
        """Draft KeyValidate: decodes, subgroup-checks, and rejects the
        infinity (zero) pubkey. Cached — validator sets re-verify every
        height."""
        if self._valid is None:
            self._valid = _bls.bls_pubkey_validate(self._bytes)
        return self._valid

    def verify_signature(self, msg: bytes, sig: bytes) -> bool:
        if len(sig) != SIGNATURE_SIZE:
            return False
        if type(msg) is not bytes:
            msg = bytes(msg)  # shared-prefix factored rows (prefixrows)
        if not self.key_validate():
            return False
        return _bls.bls_verify(self._bytes, msg, sig, DST)

    def __repr__(self) -> str:
        return f"PubKeyBLS12381{{{self._bytes.hex().upper()}}}"


class PrivKey(crypto.PrivKey):
    __slots__ = ("_bytes", "_sk", "_pub")

    def __init__(self, data: bytes):
        if len(data) != PRIV_KEY_SIZE:
            raise crypto.ErrInvalidKey("bls12381 privkey must be 32 bytes")
        self._bytes = bytes(data)
        self._sk = int.from_bytes(self._bytes, "big") % _bls.BLS_R
        if self._sk == 0:
            raise crypto.ErrInvalidKey("bls12381 privkey is zero mod r")
        self._pub = PubKey(_bls.bls_pub_from_priv(self._sk))

    def bytes_(self) -> bytes:
        return self._bytes

    def sign(self, msg: bytes) -> bytes:
        if type(msg) is not bytes:
            msg = bytes(msg)
        return _bls.bls_sign(self._sk, msg, DST)

    def pub_key(self) -> PubKey:
        return self._pub

    def type_(self) -> str:
        return KEY_TYPE


def gen_priv_key() -> PrivKey:
    while True:
        data = secrets.token_bytes(PRIV_KEY_SIZE)
        if int.from_bytes(data, "big") % _bls.BLS_R:
            return PrivKey(data)


def gen_priv_key_from_secret(secret: bytes) -> PrivKey:
    """Deterministic key from a secret (testing only)."""
    return PrivKey(hashlib.sha256(secret).digest())


def aggregate_signatures(sigs: list[bytes]) -> bytes:
    """One 96-byte aggregate from per-vote signatures (each individually
    subgroup-checked; infinity and garbage raise ValueError)."""
    return _bls.bls_aggregate(sigs)


def aggregate_verify(pubs: list[bytes], msgs: list[bytes],
                     sig: bytes) -> bool:
    """The one-pairing-product commit check (PoP flavor: repeated
    messages aggregate their pubkeys). See fallback.bls_aggregate_verify
    for the exact rejection semantics (infinity pubkey/signature, wrong
    subgroup, cancelled pubkey group)."""
    return _bls.bls_aggregate_verify(
        [bytes(p) for p in pubs], [bytes(m) for m in msgs], bytes(sig), DST)


class CPUBatchVerifier(crypto.BatchVerifier):
    """CPU batched single-verify: a random-linear-combination check with
    ONE shared final exponentiation —

        e(-g1, sum [r_i] sig_i) * prod e([r_i] pk_i, H(m_i)) == 1

    with fresh 128-bit blinders r_i (a forged row passes only with
    probability 2^-128). On failure the verifier pinpoints per-lane with
    serial exact verifies, mirroring the device kernels' mask contract."""

    def __init__(self) -> None:
        self._items: list[tuple[PubKey, bytes, bytes]] = []

    def add(self, pub_key: crypto.PubKey, msg: bytes, sig: bytes) -> None:
        if not isinstance(pub_key, PubKey):
            raise crypto.ErrInvalidKey(
                "bls12381 batch verifier got non-bls12381 key")
        if len(sig) != SIGNATURE_SIZE:
            raise crypto.ErrInvalidSignature("bad signature length")
        self._items.append((pub_key, msg, sig))

    def count(self) -> int:
        return len(self._items)

    def verify(self) -> tuple[bool, list[bool]]:
        n = len(self._items)
        if n == 0:
            return True, []
        if self._combined_check():
            return True, [True] * n
        mask = [pk.verify_signature(m, s) for pk, m, s in self._items]
        return all(mask), mask

    def _combined_check(self) -> bool:
        f = _bls
        sig_acc = None
        pairs = []
        h_cache: dict[bytes, tuple] = {}
        for pk, msg, sig in self._items:
            if not pk.key_validate():
                return False
            sig_aff = f.bls_signature_validate(sig)
            if sig_aff is None:
                return False
            r = secrets.randbits(128) | 1
            sig_acc = f._ec_add(
                f._Fp2Ops, sig_acc,
                f._ec_mul(f._Fp2Ops, r, f._ec_from_affine(sig_aff)))
            msg_b = bytes(msg)
            h = h_cache.get(msg_b)
            if h is None:
                h = f.bls_hash_to_g2(msg_b, DST)
                h_cache[msg_b] = h
            pk_r = f._ec_affine(f._FpOps, f._ec_mul(
                f._FpOps, r, f._ec_from_affine(f.bls_g1_decompress(pk.bytes_()))))
            pairs.append((pk_r, h))
        agg_sig = f._ec_affine(f._Fp2Ops, sig_acc)
        if agg_sig is None:
            return False
        pairs.append((f._NEG_G1, agg_sig))
        return f.bls_pairing_product_is_one(pairs)
