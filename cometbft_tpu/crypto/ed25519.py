"""Ed25519 keys (reference: crypto/ed25519/ed25519.go).

Signing and the production single-verify fast path are OpenSSL-backed (the
`cryptography` package); batch verification routes through crypto/batch to
either the TPU kernel (ops/) or a CPU fallback. Key type string, sizes and
address derivation mirror the reference.

Verification-semantics note: the reference verifies under ZIP-215
(ed25519.go:37-42). OpenSSL's verify is cofactorless-strict; the two agree on
all signatures produced by honest signers and on random forgeries, and differ
only on adversarial edge-case encodings (non-canonical points, small-order
components). verify_signature therefore first tries OpenSSL and, only on
rejection, re-checks under the pure ZIP-215 oracle so that accept/reject
behavior is exactly ZIP-215 while the hot path stays native-speed.
"""

from __future__ import annotations

import hashlib
import secrets

try:
    from cryptography.exceptions import InvalidSignature
    from cryptography.hazmat.primitives.asymmetric.ed25519 import (
        Ed25519PrivateKey,
        Ed25519PublicKey,
    )

    _HAVE_OPENSSL = True
except ImportError:  # degraded: pure-Python ZIP-215 oracle does everything
    _HAVE_OPENSSL = False

from cometbft_tpu import crypto
from cometbft_tpu.crypto import ed25519_math, tmhash

KEY_TYPE = "ed25519"
PUB_KEY_SIZE = 32
PRIV_KEY_SIZE = 64  # seed || pubkey, matching Go's ed25519.PrivateKey layout
SIGNATURE_SIZE = 64
SEED_SIZE = 32


class PubKey(crypto.PubKey):
    __slots__ = ("_bytes", "_openssl")

    def __init__(self, data: bytes):
        if len(data) != PUB_KEY_SIZE:
            raise crypto.ErrInvalidKey(f"ed25519 pubkey must be {PUB_KEY_SIZE} bytes")
        self._bytes = bytes(data)
        self._openssl: Ed25519PublicKey | None = None

    def address(self) -> bytes:
        return tmhash.sum_truncated(self._bytes)

    def bytes_(self) -> bytes:
        return self._bytes

    def type_(self) -> str:
        return KEY_TYPE

    def verify_signature(self, msg: bytes, sig: bytes) -> bool:
        if len(sig) != SIGNATURE_SIZE:
            return False
        if type(msg) is not bytes:
            msg = bytes(msg)  # shared-prefix factored rows (prefixrows)
        if not _HAVE_OPENSSL:
            from cometbft_tpu.crypto import _libcrypto

            if _libcrypto.available():
                # same strict-then-ZIP-215 split as the cryptography path
                if _libcrypto.ed25519_verify(self._bytes, msg, sig):
                    return True
            if int.from_bytes(sig[32:], "little") >= ed25519_math.L:
                return False
            return ed25519_math.verify_zip215(self._bytes, msg, sig)
        try:
            if self._openssl is None:
                self._openssl = Ed25519PublicKey.from_public_bytes(self._bytes)
            self._openssl.verify(sig, msg)
            return True
        except (InvalidSignature, ValueError):
            # OpenSSL rejected: re-check under ZIP-215, which accepts a
            # superset (non-canonical R/A encodings, cofactored equation).
            # The S >= L pre-filter is free and final under both semantics,
            # so ~15/16 of random garbage never reaches the slow oracle.
            # Residual cost: a crafted canonical-looking bad sig costs ~1 ms
            # of Python bignum math; consensus callers ban the sending peer
            # on the first invalid signature, bounding the amplification.
            # Roadmap: native C++ ZIP-215 verifier removes the gap entirely.
            if int.from_bytes(sig[32:], "little") >= ed25519_math.L:
                return False
            return ed25519_math.verify_zip215(self._bytes, msg, sig)

    def __repr__(self) -> str:
        return f"PubKeyEd25519{{{self._bytes.hex().upper()}}}"


class PrivKey(crypto.PrivKey):
    __slots__ = ("_seed", "_pub", "_openssl")

    def __init__(self, data: bytes):
        # Accept 32-byte seed or 64-byte seed||pub (Go layout).
        if len(data) == SEED_SIZE:
            seed = bytes(data)
        elif len(data) == PRIV_KEY_SIZE:
            seed = bytes(data[:SEED_SIZE])
        else:
            raise crypto.ErrInvalidKey("ed25519 privkey must be 32 or 64 bytes")
        self._seed = seed
        if _HAVE_OPENSSL:
            self._openssl = Ed25519PrivateKey.from_private_bytes(seed)
            pub = self._openssl.public_key().public_bytes_raw()
        else:
            from cometbft_tpu.crypto import _libcrypto

            self._openssl = None
            if _libcrypto.available():
                pub = _libcrypto.ed25519_pub_from_seed(seed)
            else:
                pub = ed25519_math.public_key_from_seed(seed)
        self._pub = PubKey(pub)

    def bytes_(self) -> bytes:
        return self._seed + self._pub.bytes_()

    def sign(self, msg: bytes) -> bytes:
        if self._openssl is None:
            from cometbft_tpu.crypto import _libcrypto

            if _libcrypto.available():
                return _libcrypto.ed25519_sign(self._seed, msg)
            return ed25519_math.sign(self._seed, msg)
        return self._openssl.sign(msg)

    def pub_key(self) -> PubKey:
        return self._pub

    def type_(self) -> str:
        return KEY_TYPE


def gen_priv_key() -> PrivKey:
    return PrivKey(secrets.token_bytes(SEED_SIZE))


def gen_priv_key_from_secret(secret: bytes) -> PrivKey:
    """Deterministic key from a secret (reference: GenPrivKeyFromSecret,
    ed25519.go:162-170 — seed = SHA256(secret)). Testing only."""
    return PrivKey(hashlib.sha256(secret).digest())


class CPUBatchVerifier(crypto.BatchVerifier):
    """CPU fallback: OpenSSL per-signature loop with ZIP-215 re-check on
    rejection. Matches reference BatchVerifier semantics (all-or-mask)."""

    def __init__(self) -> None:
        self._items: list[tuple[PubKey, bytes, bytes]] = []

    def add(self, pub_key: crypto.PubKey, msg: bytes, sig: bytes) -> None:
        if not isinstance(pub_key, PubKey):
            raise crypto.ErrInvalidKey("ed25519 batch verifier got non-ed25519 key")
        if len(sig) != SIGNATURE_SIZE:
            raise crypto.ErrInvalidSignature("bad signature length")
        self._items.append((pub_key, msg, sig))

    def verify(self) -> tuple[bool, list[bool]]:
        mask = [pk.verify_signature(msg, sig) for pk, msg, sig in self._items]
        return all(mask), mask

    def count(self) -> int:
        return len(self._items)
