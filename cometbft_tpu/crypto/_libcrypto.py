"""ctypes bindings to the process's libcrypto (OpenSSL >= 1.1.1).

The `cryptography` wheel is the preferred native backend, but hosts that
lack it almost always still carry libcrypto — CPython's own `ssl` module
links it. This shim reaches the three primitives the hot paths need
(ChaCha20-Poly1305, Ed25519, X25519) through the EVP interface so the
pure-Python rungs in crypto/fallback.py are a last resort, not the first
fallback: the p2p secret connection pushes every wire byte through the
AEAD and consensus signs/verifies per vote, so the ~50x between bignum
Python and native EVP is the difference between a test net committing in
milliseconds versus seconds per height.

All entry points degrade: `available()` is False when libcrypto or any
required symbol is missing, and callers fall through to the pure path.
"""

from __future__ import annotations

import ctypes
import ctypes.util
import threading

_EVP_CTRL_AEAD_SET_IVLEN = 0x9
_EVP_CTRL_AEAD_GET_TAG = 0x10
_EVP_CTRL_AEAD_SET_TAG = 0x11
_EVP_PKEY_X25519 = 1034
_EVP_PKEY_ED25519 = 1087

_lib = None
_lib_lock = threading.Lock()
_checked = False


def _load():
    global _lib, _checked
    if _checked:
        return _lib
    with _lib_lock:
        if _checked:
            return _lib
        try:
            name = ctypes.util.find_library("crypto") or "libcrypto.so"
            lib = ctypes.CDLL(name)
            # the full symbol surface this module uses; AttributeError on
            # any -> no libcrypto backend
            lib.EVP_CIPHER_CTX_new.restype = ctypes.c_void_p
            lib.EVP_CIPHER_CTX_free.argtypes = [ctypes.c_void_p]
            lib.EVP_chacha20_poly1305.restype = ctypes.c_void_p
            for fn in ("EVP_EncryptInit_ex", "EVP_DecryptInit_ex"):
                getattr(lib, fn).argtypes = [
                    ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
                    ctypes.c_char_p, ctypes.c_char_p]
            for fn in ("EVP_EncryptUpdate", "EVP_DecryptUpdate"):
                getattr(lib, fn).argtypes = [
                    ctypes.c_void_p, ctypes.c_char_p,
                    ctypes.POINTER(ctypes.c_int), ctypes.c_char_p,
                    ctypes.c_int]
            lib.EVP_EncryptFinal_ex.argtypes = [
                ctypes.c_void_p, ctypes.c_char_p,
                ctypes.POINTER(ctypes.c_int)]
            lib.EVP_DecryptFinal_ex.argtypes = [
                ctypes.c_void_p, ctypes.c_char_p,
                ctypes.POINTER(ctypes.c_int)]
            lib.EVP_CIPHER_CTX_ctrl.argtypes = [
                ctypes.c_void_p, ctypes.c_int, ctypes.c_int, ctypes.c_void_p]
            lib.EVP_PKEY_new_raw_private_key.restype = ctypes.c_void_p
            lib.EVP_PKEY_new_raw_private_key.argtypes = [
                ctypes.c_int, ctypes.c_void_p, ctypes.c_char_p,
                ctypes.c_size_t]
            lib.EVP_PKEY_new_raw_public_key.restype = ctypes.c_void_p
            lib.EVP_PKEY_new_raw_public_key.argtypes = [
                ctypes.c_int, ctypes.c_void_p, ctypes.c_char_p,
                ctypes.c_size_t]
            lib.EVP_PKEY_get_raw_public_key.argtypes = [
                ctypes.c_void_p, ctypes.c_char_p,
                ctypes.POINTER(ctypes.c_size_t)]
            lib.EVP_PKEY_free.argtypes = [ctypes.c_void_p]
            lib.EVP_MD_CTX_new.restype = ctypes.c_void_p
            lib.EVP_MD_CTX_free.argtypes = [ctypes.c_void_p]
            lib.EVP_DigestSignInit.argtypes = [
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
                ctypes.c_void_p, ctypes.c_void_p]
            lib.EVP_DigestVerifyInit.argtypes = [
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
                ctypes.c_void_p, ctypes.c_void_p]
            lib.EVP_DigestSign.argtypes = [
                ctypes.c_void_p, ctypes.c_char_p,
                ctypes.POINTER(ctypes.c_size_t), ctypes.c_char_p,
                ctypes.c_size_t]
            lib.EVP_DigestVerify.argtypes = [
                ctypes.c_void_p, ctypes.c_char_p, ctypes.c_size_t,
                ctypes.c_char_p, ctypes.c_size_t]
            lib.EVP_PKEY_CTX_new.restype = ctypes.c_void_p
            lib.EVP_PKEY_CTX_new.argtypes = [ctypes.c_void_p, ctypes.c_void_p]
            lib.EVP_PKEY_CTX_free.argtypes = [ctypes.c_void_p]
            lib.EVP_PKEY_derive_init.argtypes = [ctypes.c_void_p]
            lib.EVP_PKEY_derive_set_peer.argtypes = [
                ctypes.c_void_p, ctypes.c_void_p]
            lib.EVP_PKEY_derive.argtypes = [
                ctypes.c_void_p, ctypes.c_char_p,
                ctypes.POINTER(ctypes.c_size_t)]
            _lib = lib
        except (OSError, AttributeError):
            _lib = None
        _checked = True
        return _lib


def available() -> bool:
    return _load() is not None


# ------------------------------------------------------------------- AEAD


def aead_seal(key: bytes, nonce12: bytes, data: bytes, aad: bytes) -> bytes:
    """ChaCha20-Poly1305 seal -> ciphertext || 16-byte tag."""
    lib = _load()
    ctx = lib.EVP_CIPHER_CTX_new()
    try:
        outl = ctypes.c_int(0)
        if not lib.EVP_EncryptInit_ex(
                ctx, lib.EVP_chacha20_poly1305(), None, None, None):
            raise RuntimeError("EVP init failed")
        lib.EVP_CIPHER_CTX_ctrl(ctx, _EVP_CTRL_AEAD_SET_IVLEN, 12, None)
        if not lib.EVP_EncryptInit_ex(ctx, None, None, key, nonce12):
            raise RuntimeError("EVP key/iv init failed")
        if aad:
            lib.EVP_EncryptUpdate(ctx, None, ctypes.byref(outl), aad, len(aad))
        out = ctypes.create_string_buffer(len(data) + 16)
        n = 0
        if data:
            lib.EVP_EncryptUpdate(ctx, out, ctypes.byref(outl), data, len(data))
            n = outl.value
        fin = ctypes.create_string_buffer(16)
        lib.EVP_EncryptFinal_ex(ctx, fin, ctypes.byref(outl))
        tag = ctypes.create_string_buffer(16)
        lib.EVP_CIPHER_CTX_ctrl(ctx, _EVP_CTRL_AEAD_GET_TAG, 16, tag)
        return out.raw[:n] + tag.raw
    finally:
        lib.EVP_CIPHER_CTX_free(ctx)


def aead_open(key: bytes, nonce12: bytes, data: bytes, aad: bytes) -> bytes:
    """ChaCha20-Poly1305 open; raises ValueError on a bad tag."""
    lib = _load()
    if len(data) < 16:
        raise ValueError("ciphertext too short")
    ct, tag = data[:-16], data[-16:]
    ctx = lib.EVP_CIPHER_CTX_new()
    try:
        outl = ctypes.c_int(0)
        if not lib.EVP_DecryptInit_ex(
                ctx, lib.EVP_chacha20_poly1305(), None, None, None):
            raise RuntimeError("EVP init failed")
        lib.EVP_CIPHER_CTX_ctrl(ctx, _EVP_CTRL_AEAD_SET_IVLEN, 12, None)
        if not lib.EVP_DecryptInit_ex(ctx, None, None, key, nonce12):
            raise RuntimeError("EVP key/iv init failed")
        if aad:
            lib.EVP_DecryptUpdate(ctx, None, ctypes.byref(outl), aad, len(aad))
        out = ctypes.create_string_buffer(max(1, len(ct)))
        n = 0
        if ct:
            lib.EVP_DecryptUpdate(ctx, out, ctypes.byref(outl), ct, len(ct))
            n = outl.value
        tag_buf = ctypes.create_string_buffer(tag, 16)
        lib.EVP_CIPHER_CTX_ctrl(ctx, _EVP_CTRL_AEAD_SET_TAG, 16, tag_buf)
        fin = ctypes.create_string_buffer(16)
        if lib.EVP_DecryptFinal_ex(ctx, fin, ctypes.byref(outl)) <= 0:
            raise ValueError("chacha20poly1305: tag mismatch")
        return out.raw[:n]
    finally:
        lib.EVP_CIPHER_CTX_free(ctx)


# ---------------------------------------------------------------- ed25519


def ed25519_pub_from_seed(seed: bytes) -> bytes:
    lib = _load()
    pkey = lib.EVP_PKEY_new_raw_private_key(
        _EVP_PKEY_ED25519, None, seed, 32)
    if not pkey:
        raise ValueError("bad ed25519 seed")
    try:
        buf = ctypes.create_string_buffer(32)
        ln = ctypes.c_size_t(32)
        if not lib.EVP_PKEY_get_raw_public_key(pkey, buf, ctypes.byref(ln)):
            raise RuntimeError("raw public key extraction failed")
        return buf.raw[:ln.value]
    finally:
        lib.EVP_PKEY_free(pkey)


def ed25519_sign(seed: bytes, msg: bytes) -> bytes:
    lib = _load()
    pkey = lib.EVP_PKEY_new_raw_private_key(
        _EVP_PKEY_ED25519, None, seed, 32)
    if not pkey:
        raise ValueError("bad ed25519 seed")
    md = lib.EVP_MD_CTX_new()
    try:
        if not lib.EVP_DigestSignInit(md, None, None, None, pkey):
            raise RuntimeError("DigestSignInit failed")
        sig = ctypes.create_string_buffer(64)
        ln = ctypes.c_size_t(64)
        if not lib.EVP_DigestSign(md, sig, ctypes.byref(ln), msg, len(msg)):
            raise RuntimeError("DigestSign failed")
        return sig.raw[:ln.value]
    finally:
        lib.EVP_MD_CTX_free(md)
        lib.EVP_PKEY_free(pkey)


def ed25519_verify(pub: bytes, msg: bytes, sig: bytes) -> bool:
    """OpenSSL-strict (cofactorless) verify — callers re-check rejections
    under the ZIP-215 oracle exactly as with the `cryptography` backend."""
    lib = _load()
    pkey = lib.EVP_PKEY_new_raw_public_key(_EVP_PKEY_ED25519, None, pub, 32)
    if not pkey:
        return False
    md = lib.EVP_MD_CTX_new()
    try:
        if not lib.EVP_DigestVerifyInit(md, None, None, None, pkey):
            return False
        return lib.EVP_DigestVerify(md, sig, len(sig), msg, len(msg)) == 1
    finally:
        lib.EVP_MD_CTX_free(md)
        lib.EVP_PKEY_free(pkey)


# ----------------------------------------------------------------- x25519


def x25519_pub(scalar: bytes) -> bytes:
    lib = _load()
    pkey = lib.EVP_PKEY_new_raw_private_key(
        _EVP_PKEY_X25519, None, scalar, 32)
    if not pkey:
        raise ValueError("bad x25519 scalar")
    try:
        buf = ctypes.create_string_buffer(32)
        ln = ctypes.c_size_t(32)
        if not lib.EVP_PKEY_get_raw_public_key(pkey, buf, ctypes.byref(ln)):
            raise RuntimeError("raw public key extraction failed")
        return buf.raw[:ln.value]
    finally:
        lib.EVP_PKEY_free(pkey)


def x25519(scalar: bytes, point: bytes) -> bytes:
    """X25519(k, u); raises ValueError on the all-zero shared secret (the
    same contract as cryptography's exchange())."""
    lib = _load()
    pkey = lib.EVP_PKEY_new_raw_private_key(
        _EVP_PKEY_X25519, None, scalar, 32)
    peer = lib.EVP_PKEY_new_raw_public_key(_EVP_PKEY_X25519, None, point, 32)
    if not pkey or not peer:
        for p in (pkey, peer):
            if p:
                lib.EVP_PKEY_free(p)
        raise ValueError("bad x25519 key material")
    ctx = lib.EVP_PKEY_CTX_new(pkey, None)
    try:
        if (lib.EVP_PKEY_derive_init(ctx) <= 0
                or lib.EVP_PKEY_derive_set_peer(ctx, peer) <= 0):
            raise ValueError("x25519 derive init failed")
        out = ctypes.create_string_buffer(32)
        ln = ctypes.c_size_t(32)
        if lib.EVP_PKEY_derive(ctx, out, ctypes.byref(ln)) <= 0:
            raise ValueError("x25519: derive failed (low-order point)")
        return out.raw[:ln.value]
    finally:
        lib.EVP_PKEY_CTX_free(ctx)
        lib.EVP_PKEY_free(peer)
        lib.EVP_PKEY_free(pkey)
