"""Crypto plugin boundary (reference: crypto/crypto.go:22-53).

PubKey / PrivKey / BatchVerifier are the seams the rest of the framework
programs against; concrete schemes (ed25519, sr25519, secp256k1) register
here, and `crypto.batch` picks a batch verifier by key type AND configured
backend ("cpu" | "tpu" | "auto") — the north-star plugin point
(reference: crypto/batch/batch.go:11-32).

Batch-first design difference from the reference: BatchVerifier.add() is
cheap staging only; verify() is the sync point and returns BOTH the overall
bool and a per-signature validity mask (the reference falls back to serial
re-verification to pinpoint bad signatures — types/validation.go:266; on TPU
the mask is free, it's the kernel's lane output).
"""

from __future__ import annotations

from abc import ABC, abstractmethod

# Address: 20-byte truncated SHA-256 of the pubkey bytes
# (reference: crypto/crypto.go:8-17, crypto/tmhash).
ADDRESS_SIZE = 20

# Wire cap on signature bytes in votes/commits/proposals. The reference
# pins 64 (ed25519/sr25519); BLS12-381 G2 signatures are 96 bytes, so
# the cap is the max over registered schemes — validate_basic callers
# share this constant instead of baking the ed25519 size.
MAX_SIGNATURE_SIZE = 96


class PubKey(ABC):
    @abstractmethod
    def address(self) -> bytes: ...

    @abstractmethod
    def bytes_(self) -> bytes: ...

    @abstractmethod
    def verify_signature(self, msg: bytes, sig: bytes) -> bool: ...

    @abstractmethod
    def type_(self) -> str: ...

    def __eq__(self, other: object) -> bool:
        return (isinstance(other, PubKey) and self.type_() == other.type_()
                and self.bytes_() == other.bytes_())

    def __hash__(self) -> int:
        return hash((self.type_(), self.bytes_()))


class PrivKey(ABC):
    @abstractmethod
    def bytes_(self) -> bytes: ...

    @abstractmethod
    def sign(self, msg: bytes) -> bytes: ...

    @abstractmethod
    def pub_key(self) -> PubKey: ...

    @abstractmethod
    def type_(self) -> str: ...


class BatchVerifier(ABC):
    """Accumulate (pubkey, msg, sig) triples; verify once.

    add() validates shapes and stages host-side; verify() flushes to the
    backend (device batch or CPU loop) and returns (all_valid, per_sig_mask).
    """

    @abstractmethod
    def add(self, pub_key: PubKey, msg: bytes, sig: bytes) -> None: ...

    @abstractmethod
    def verify(self) -> tuple[bool, list[bool]]: ...

    @abstractmethod
    def count(self) -> int: ...


class ErrInvalidKey(Exception):
    pass


class ErrInvalidSignature(Exception):
    pass
