"""Batch-verifier dispatch (reference: crypto/batch/batch.go:11-32).

The reference keys verifier creation on pubkey *type*; this framework adds the
backend dimension — "cpu" (OpenSSL loop), "tpu" (JAX/Pallas device kernel),
or "auto" (tpu when an accelerator is present, else cpu). The chosen backend
is process-global, set once from config (config.crypto.backend) at node boot.
"""

from __future__ import annotations

from typing import Callable, Optional

from cometbft_tpu import crypto
from cometbft_tpu.crypto import ed25519
from cometbft_tpu.libs.prefixrows import PrefixedMsg

_BACKEND = "auto"
_tpu_available: Optional[bool] = None

# key type -> backend name -> factory
_REGISTRY: dict[str, dict[str, Callable[[], crypto.BatchVerifier]]] = {}


def register(key_type: str, backend: str,
             factory: Callable[[], crypto.BatchVerifier]) -> None:
    _REGISTRY.setdefault(key_type, {})[backend] = factory


def set_backend(backend: str) -> None:
    global _BACKEND
    if backend not in ("auto", "cpu", "tpu"):
        raise ValueError(f"unknown crypto backend {backend!r}")
    _BACKEND = backend


def get_backend() -> str:
    return _BACKEND


def _device_present() -> bool:
    global _tpu_available
    if _tpu_available is None:
        try:
            import jax

            _tpu_available = any(d.platform != "cpu" for d in jax.devices())
        except Exception:  # noqa: BLE001 - no jax / no device: fall back
            _tpu_available = False
    return _tpu_available


def resolve_backend() -> str:
    """The backend that a batch staged NOW should target. "auto" prefers
    the device when one is present; either way a "tpu" resolution defers
    to the device supervisor's circuit breaker (ops/dispatch.py) — while
    the breaker is open the whole node runs the CPU ladder, and the
    half-open re-probe window routes batches back to the device so a
    recovered chip is reclaimed."""
    backend = _BACKEND
    if backend == "auto":
        backend = "tpu" if _device_present() else "cpu"
    if backend == "tpu":
        from cometbft_tpu.ops import dispatch

        if not dispatch.device_allowed():
            backend = "cpu"
    _publish_active(backend)
    return backend


def _publish_active(backend: str) -> None:
    try:
        from cometbft_tpu.libs import metrics

        g = metrics.crypto_metrics().backend_active
        for b in ("cpu", "tpu"):
            g.labels(b).set(1.0 if b == backend else 0.0)
    except Exception:  # noqa: BLE001 - metrics must never break dispatch
        pass


def configure(crypto_cfg) -> None:
    """Apply config.crypto at node boot: backend selection, supervision
    knobs (retry/backoff/breaker/watchdog), verify-scheduler knobs, and
    any chaos schedule."""
    set_backend(crypto_cfg.backend)
    from cometbft_tpu.ops import dispatch

    dispatch.configure(
        failure_threshold=crypto_cfg.breaker_failure_threshold,
        cooldown=crypto_cfg.breaker_cooldown,
        retry_attempts=crypto_cfg.retry_max_attempts,
        retry_base=crypto_cfg.retry_backoff_base,
        retry_cap=crypto_cfg.retry_backoff_cap,
        watchdog_timeout=crypto_cfg.watchdog_timeout,
    )
    from cometbft_tpu import sched

    sched.configure(
        enabled=crypto_cfg.scheduler,
        max_lanes=crypto_cfg.sched_max_lanes,
        sync_deadline=crypto_cfg.sched_sync_deadline,
        light_deadline=crypto_cfg.sched_light_deadline,
        mempool_deadline=crypto_cfg.sched_mempool_deadline,
        queue_limit=crypto_cfg.sched_queue_limit,
        starvation_limit=crypto_cfg.sched_starvation_limit,
    )
    from cometbft_tpu.parallel import mesh as verify_mesh

    verify_mesh.configure(
        enabled=crypto_cfg.mesh_enabled,
        min_devices=crypto_cfg.mesh_min_devices,
        placement=crypto_cfg.mesh_placement,
    )
    from cometbft_tpu.ops import residency

    residency.configure(
        enabled=crypto_cfg.wire_indexed_sends,
        rows=crypto_cfg.wire_table_rows,
    )
    from cometbft_tpu.ops import challenge

    challenge.configure(
        enabled=crypto_cfg.wire_device_challenge,
    )
    from cometbft_tpu.crypto import bls12381

    bls12381.set_enabled(crypto_cfg.bls_enabled)
    if crypto_cfg.chaos:
        from cometbft_tpu.libs import chaos

        chaos.arm_spec(crypto_cfg.chaos)


def _check_bls_enabled(key_type: str) -> None:
    """A BLS key arriving with crypto.bls_enabled off is a CONFIGURATION
    error and must fail loudly (the light-proxy https-refusal rule) —
    a silent CPU fallback would hide that aggregate commit verification
    is off while the validator set expects it."""
    if key_type != "bls12381":
        return
    from cometbft_tpu.crypto import bls12381

    if not bls12381.enabled():
        raise crypto.ErrInvalidKey(
            "bls12381 key reached the batch-verify seam but the scheme is "
            "disabled (crypto.bls_enabled = false); enable it in config "
            "or remove BLS keys from the validator set")


def supports_batch_verifier(pub_key: crypto.PubKey | None) -> bool:
    """reference: crypto/batch/batch.go:26-32 — secp256k1 has no batch
    path. Raises ErrInvalidKey (not False) for a BLS key while
    crypto.bls_enabled is off: misconfiguration must be loud."""
    if pub_key is None:
        return False
    _check_bls_enabled(pub_key.type_())
    return pub_key.type_() in _REGISTRY


def create_batch_verifier(pub_key: crypto.PubKey) -> crypto.BatchVerifier:
    """Create a verifier for this key type on the configured backend.
    Raises ErrInvalidKey for unbatchable key types (caller falls back to
    serial verification, as the reference does).

    With the global verify scheduler enabled (the default) the returned
    verifier is a CLIENT of the node-wide scheduler: verify() drains as
    one inline batch that coalesces whatever compatible queued work fits
    the bucket (sched/scheduler.py). The producer no longer owns device
    dispatch — that inversion is what keeps the device running few full
    batches instead of many fragmented ones."""
    backends = _REGISTRY.get(pub_key.type_())
    if not backends:
        raise crypto.ErrInvalidKey(
            f"key type {pub_key.type_()!r} has no batch verifier")
    from cometbft_tpu import sched

    if sched.enabled():
        return ScheduledBatchVerifier()
    backend = resolve_backend()
    factory = backends.get(backend) or backends["cpu"]
    try:
        return factory()
    except Exception:  # noqa: BLE001 - device backend unavailable/broken
        if backend == "cpu":
            raise
        return backends["cpu"]()


class MixedBatchVerifier(crypto.BatchVerifier):
    """Coalesces a mixed-scheme batch (BASELINE config 5: ed25519+sr25519
    mega-commits): add() routes each row to a per-type sub-verifier on the
    configured backend; verify() runs every sub-batch and stitches the
    per-lane masks back into input order. On the TPU backend each scheme is
    one device batch — a mixed 10k-commit costs two kernel dispatches, not
    10k serial verifies."""

    def __init__(self):
        self._subs: dict[str, crypto.BatchVerifier] = {}
        self._route: list[tuple[str, int]] = []  # (key type, index in sub)

    def add(self, pub_key: crypto.PubKey, msg: bytes, sig: bytes) -> None:
        kt = pub_key.type_()
        _check_bls_enabled(kt)
        sub = self._subs.get(kt)
        if sub is None:
            backends = _REGISTRY.get(kt)
            if not backends:
                raise crypto.ErrInvalidKey(f"key type {kt!r} has no batch verifier")
            backend = resolve_backend()
            sub = (backends.get(backend) or backends["cpu"])()
            self._subs[kt] = sub
        sub.add(pub_key, msg, sig)
        self._route.append((kt, sub.count() - 1))

    def verify(self) -> tuple[bool, list[bool]]:
        if len(self._subs) > 1 and all(
            hasattr(sub, "verify_async") for sub in self._subs.values()
        ):
            # device backends: dispatch every scheme's sub-batch without
            # blocking, then resolve ALL masks with one device->host fetch
            # (over a high-RTT link the serial per-scheme sync path paid
            # one full round trip per scheme)
            from cometbft_tpu.ops import ed25519_kernel

            thunks = {kt: sub.verify_async() for kt, sub in self._subs.items()}
            resolved = ed25519_kernel.resolve_batches(list(thunks.values()))
            masks = {kt: m for kt, m in zip(thunks, resolved)}
        else:
            masks = {kt: sub.verify()[1] for kt, sub in self._subs.items()}
        out = [bool(masks[kt][i]) for kt, i in self._route]
        return all(out), out

    def count(self) -> int:
        return len(self._route)


class ScheduledBatchVerifier(crypto.BatchVerifier):
    """The scheduler-client face of crypto.BatchVerifier: add() stages
    rows host-side (cheap structural checks, same contract as the CPU/TPU
    verifiers); verify() submits the rows to the global VerifyScheduler
    as ONE group under the caller's ambient priority class
    (sched.work_class) and drains inline, coalescing queued filler.
    Mixed key types are accepted — the scheduler groups rows per scheme
    into per-scheme device sub-batches resolved with one fetch."""

    # per-scheme signature sizes (BLS G2 signatures are 96 bytes)
    SIGNATURE_SIZES = {"ed25519": 64, "sr25519": 64, "bls12381": 96}

    def __init__(self, klass: str | None = None):
        from cometbft_tpu import sched

        self._klass = klass or sched.current_class()
        self._rows: list[tuple[crypto.PubKey, bytes, bytes]] = []

    def add(self, pub_key: crypto.PubKey, msg: bytes, sig: bytes) -> None:
        kt = pub_key.type_()
        _check_bls_enabled(kt)
        if kt not in _REGISTRY:
            raise crypto.ErrInvalidKey(
                f"key type {kt!r} has no batch verifier")
        if len(sig) != self.SIGNATURE_SIZES.get(kt, 64):
            raise crypto.ErrInvalidSignature("bad signature length")
        # shared-prefix rows (libs/prefixrows.py) ride to the scheduler
        # factored — kernel staging broadcasts each run's prefix once
        self._rows.append((
            pub_key,
            msg if isinstance(msg, PrefixedMsg) else bytes(msg),
            bytes(sig)))

    def verify(self) -> tuple[bool, list[bool]]:
        if not self._rows:
            return True, []
        from cometbft_tpu import sched

        mask = sched.get().verify_now(self._rows, self._klass)
        out = [bool(x) for x in mask]
        return all(out), out

    def count(self) -> int:
        return len(self._rows)


def create_mixed_batch_verifier() -> crypto.BatchVerifier:
    from cometbft_tpu import sched

    if sched.enabled():
        return ScheduledBatchVerifier()
    return MixedBatchVerifier()


def _tpu_ed25519_factory() -> crypto.BatchVerifier:
    from cometbft_tpu.ops.batch_verifier import TPUBatchVerifier

    return TPUBatchVerifier()


def _tpu_sr25519_factory() -> crypto.BatchVerifier:
    from cometbft_tpu.ops.batch_verifier import SrTPUBatchVerifier

    return SrTPUBatchVerifier()


def _cpu_sr25519_factory() -> crypto.BatchVerifier:
    from cometbft_tpu.crypto import sr25519

    return sr25519.CPUBatchVerifier()


def _tpu_bls_factory() -> crypto.BatchVerifier:
    from cometbft_tpu.ops.batch_verifier import BlsTPUBatchVerifier

    return BlsTPUBatchVerifier()


def _cpu_bls_factory() -> crypto.BatchVerifier:
    from cometbft_tpu.crypto import bls12381

    return bls12381.CPUBatchVerifier()


register(ed25519.KEY_TYPE, "cpu", ed25519.CPUBatchVerifier)
register(ed25519.KEY_TYPE, "tpu", _tpu_ed25519_factory)
register("sr25519", "cpu", _cpu_sr25519_factory)
register("sr25519", "tpu", _tpu_sr25519_factory)
register("bls12381", "cpu", _cpu_bls_factory)
register("bls12381", "tpu", _tpu_bls_factory)
