"""sr25519 keys (reference: crypto/sr25519/{privkey,pubkey,batch}.go).

Signing and verification are backed by the schnorrkel oracle
(crypto/sr25519_math.py — Merlin transcripts over STROBE-128/Keccak, the
ristretto255 group over edwards25519); batch verification routes through
crypto/batch to the TPU kernel (ops/sr25519_kernel.py: the group equation
[4](sB - kA - R) == O is the same signed-window ladder as ed25519 with
ristretto decoding and a cofactor-4 coset check) or a CPU fallback.

Key type string, sizes, and address derivation mirror the reference
(pubkey.go:15-32: SHA256-20 of the raw ristretto bytes).
"""

from __future__ import annotations

import hashlib
import secrets

from cometbft_tpu import crypto
from cometbft_tpu.crypto import sr25519_math as srm
from cometbft_tpu.crypto import tmhash

KEY_TYPE = "sr25519"
PUB_KEY_SIZE = 32
PRIV_KEY_SIZE = 32  # the MiniSecretKey (privkey.go:21)
SIGNATURE_SIZE = 64


class PubKey(crypto.PubKey):
    __slots__ = ("_bytes",)

    def __init__(self, data: bytes):
        if len(data) != PUB_KEY_SIZE:
            raise crypto.ErrInvalidKey(f"sr25519 pubkey must be {PUB_KEY_SIZE} bytes")
        self._bytes = bytes(data)

    def address(self) -> bytes:
        return tmhash.sum_truncated(self._bytes)

    def bytes_(self) -> bytes:
        return self._bytes

    def type_(self) -> str:
        return KEY_TYPE

    def verify_signature(self, msg: bytes, sig: bytes) -> bool:
        if len(sig) != SIGNATURE_SIZE:
            return False
        if type(msg) is not bytes:
            msg = bytes(msg)  # shared-prefix factored rows (prefixrows)
        return srm.verify(self._bytes, msg, sig)

    def __repr__(self) -> str:
        return f"PubKeySr25519{{{self._bytes.hex().upper()}}}"


class PrivKey(crypto.PrivKey):
    __slots__ = ("_mini", "_pair", "_pub")

    def __init__(self, data: bytes):
        if len(data) != PRIV_KEY_SIZE:
            raise crypto.ErrInvalidKey("sr25519 privkey must be 32 bytes (mini secret)")
        self._mini = bytes(data)
        self._pair = srm.keypair_from_mini(self._mini)
        self._pub = PubKey(self._pair[2])

    def bytes_(self) -> bytes:
        return self._mini

    def sign(self, msg: bytes) -> bytes:
        return srm.sign(self._pair, msg)

    def pub_key(self) -> PubKey:
        return self._pub

    def type_(self) -> str:
        return KEY_TYPE


def gen_priv_key() -> PrivKey:
    return PrivKey(secrets.token_bytes(PRIV_KEY_SIZE))


def gen_priv_key_from_secret(secret: bytes) -> PrivKey:
    """Deterministic key from a secret (testing only)."""
    return PrivKey(hashlib.sha256(secret).digest())


class CPUBatchVerifier(crypto.BatchVerifier):
    """CPU fallback: per-signature schnorrkel verify loop."""

    def __init__(self) -> None:
        self._items: list[tuple[PubKey, bytes, bytes]] = []

    def add(self, pub_key: crypto.PubKey, msg: bytes, sig: bytes) -> None:
        if not isinstance(pub_key, PubKey):
            raise crypto.ErrInvalidKey("sr25519 batch verifier got non-sr25519 key")
        if len(sig) != SIGNATURE_SIZE:
            raise crypto.ErrInvalidSignature("bad signature length")
        self._items.append((pub_key, msg, sig))

    def verify(self) -> tuple[bool, list[bool]]:
        mask = [pk.verify_signature(msg, sig) for pk, msg, sig in self._items]
        return all(mask), mask

    def count(self) -> int:
        return len(self._items)
