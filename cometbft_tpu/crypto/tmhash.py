"""SHA-256 hashing (reference: crypto/tmhash/hash.go).

sum() is the 32-byte block/tx hash; sum_truncated() is the 20-byte prefix
used for validator addresses.
"""

import hashlib

SIZE = 32
TRUNCATED_SIZE = 20


def sum_(data: bytes) -> bytes:
    return hashlib.sha256(data).digest()


def sum_truncated(data: bytes) -> bytes:
    return hashlib.sha256(data).digest()[:TRUNCATED_SIZE]


def new():
    return hashlib.sha256()
