"""ConsensusParams (reference: types/params.go).

Consensus-critical limits agreed by the chain; hashed into Header
.ConsensusHash. The crypto section adds this framework's backend knob
surface at the *node* level only (config), never here — params must remain
chain-portable.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass, field

from cometbft_tpu.utils import protobuf as pb

MAX_BLOCK_SIZE_BYTES = 104857600  # types/params.go MaxBlockSizeBytes
ABCI_PUB_KEY_TYPE_ED25519 = "ed25519"
ABCI_PUB_KEY_TYPE_SECP256K1 = "secp256k1"
ABCI_PUB_KEY_TYPE_SR25519 = "sr25519"
ABCI_PUB_KEY_TYPE_BLS12381 = "bls12381"


@dataclass
class BlockParams:
    max_bytes: int = 22020096  # 21 MB default
    max_gas: int = -1

    def validate(self) -> None:
        if self.max_bytes == 0 or self.max_bytes < -1:
            raise ValueError(f"block.MaxBytes must be -1 or > 0. Got {self.max_bytes}")
        if self.max_bytes > MAX_BLOCK_SIZE_BYTES:
            raise ValueError(f"block.MaxBytes is too big. {self.max_bytes} > {MAX_BLOCK_SIZE_BYTES}")
        if self.max_gas < -1:
            raise ValueError(f"block.MaxGas must be >= -1. Got {self.max_gas}")


@dataclass
class EvidenceParams:
    max_age_num_blocks: int = 100000
    max_age_duration_ns: int = 48 * 3600 * 1_000_000_000  # 48h
    max_bytes: int = 1048576

    def validate(self, block_max_bytes: int) -> None:
        if self.max_age_num_blocks <= 0:
            raise ValueError("evidence.MaxAgeNumBlocks must be greater than 0")
        if self.max_age_duration_ns <= 0:
            raise ValueError("evidence.MaxAgeDuration must be greater than 0")
        if self.max_bytes > block_max_bytes:
            raise ValueError("evidence.MaxBytes exceeds block.MaxBytes")
        if self.max_bytes < 0:
            raise ValueError("evidence.MaxBytes must be non negative")


@dataclass
class ValidatorParams:
    pub_key_types: list[str] = field(default_factory=lambda: [ABCI_PUB_KEY_TYPE_ED25519])

    def validate(self) -> None:
        if not self.pub_key_types:
            raise ValueError("len(Validator.PubKeyTypes) must be greater than 0")
        for t in self.pub_key_types:
            if t not in (
                ABCI_PUB_KEY_TYPE_ED25519,
                ABCI_PUB_KEY_TYPE_SECP256K1,
                ABCI_PUB_KEY_TYPE_SR25519,
                ABCI_PUB_KEY_TYPE_BLS12381,
            ):
                raise ValueError(f"unknown pubkey type {t}")


@dataclass
class VersionParams:
    app: int = 0


@dataclass
class ABCIParams:
    vote_extensions_enable_height: int = 0

    def vote_extensions_enabled(self, height: int) -> bool:
        if self.vote_extensions_enable_height == 0:
            return False
        return height >= self.vote_extensions_enable_height


@dataclass
class ConsensusParams:
    block: BlockParams = field(default_factory=BlockParams)
    evidence: EvidenceParams = field(default_factory=EvidenceParams)
    validator: ValidatorParams = field(default_factory=ValidatorParams)
    version: VersionParams = field(default_factory=VersionParams)
    abci: ABCIParams = field(default_factory=ABCIParams)

    def validate_basic(self) -> None:
        self.block.validate()
        self.evidence.validate(self.block.max_bytes)
        self.validator.validate()

    def hash(self) -> bytes:
        """types/params.go HashConsensusParams — SHA-256 of the proto of a
        HashedParams subset (BlockMaxBytes, BlockMaxGas)."""
        w = pb.Writer()
        w.varint_i64(1, self.block.max_bytes)
        w.varint_i64(2, self.block.max_gas)
        return hashlib.sha256(w.output()).digest()

    def update(self, updates: "ConsensusParamsUpdate | None") -> "ConsensusParams":
        if updates is None:
            return self
        import copy

        res = copy.deepcopy(self)
        if updates.block is not None:
            res.block = updates.block
        if updates.evidence is not None:
            res.evidence = updates.evidence
        if updates.validator is not None:
            res.validator = updates.validator
        if updates.version is not None:
            res.version = updates.version
        if updates.abci is not None:
            res.abci = updates.abci
        return res


@dataclass
class ConsensusParamsUpdate:
    block: BlockParams | None = None
    evidence: EvidenceParams | None = None
    validator: ValidatorParams | None = None
    version: VersionParams | None = None
    abci: ABCIParams | None = None


def default_consensus_params() -> ConsensusParams:
    return ConsensusParams()
