"""Domain model: blocks, votes, commits, validator sets, evidence.

Mirrors the semantics of the reference's types/ package (SURVEY.md §2.1):
canonical protobuf sign-bytes are byte-compatible (types/canonical.go),
commit verification runs over the batch-first crypto boundary
(types/validation.go), and VoteSet accumulates signatures toward device-side
batches. The internal architecture is this framework's own.
"""

from cometbft_tpu.types.basic import (  # noqa: F401
    BlockID,
    BlockIDFlag,
    PartSetHeader,
    SignedMsgType,
    MAX_VOTES_COUNT,
)
from cometbft_tpu.types.validator import Validator, ValidatorSet  # noqa: F401
from cometbft_tpu.types.vote import Vote  # noqa: F401
from cometbft_tpu.types.commit import Commit, CommitSig, ExtendedCommit, ExtendedCommitSig  # noqa: F401
from cometbft_tpu.types.proposal import Proposal  # noqa: F401
from cometbft_tpu.types.validation import (  # noqa: F401
    verify_commit,
    verify_commit_light,
    verify_commit_light_trusting,
)
from cometbft_tpu.types.vote_set import VoteSet  # noqa: F401
from cometbft_tpu.types.block import Block, Data, EvidenceData, Header  # noqa: F401
from cometbft_tpu.types.part_set import Part, PartSet  # noqa: F401
