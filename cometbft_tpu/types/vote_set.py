"""VoteSet — vote accumulation with batch-first verification.

Reference: types/vote_set.go. The reference verifies every incoming vote
serially on the consensus goroutine (vote_set.go:218-231 — ~50-100 us each,
10k serial verifies per step at max valset, SURVEY.md §3.3). This VoteSet
keeps those semantics for add_vote() but adds the TPU-shaped path the
north-star demands:

  add_pending(vote)  — cheap structural checks + staging + SPECULATIVE tally;
                       no consensus-visible state changes.
  flush_pending()    — one batched device verification of all staged votes;
                       only then are votes added to the real tally.

The "never count an unverified vote" invariant holds: two_thirds_majority(),
get_vote(), make_commit() etc. read only verified state. The speculative
tally is used solely to decide when flushing is worthwhile (quorum boundary),
mirroring the deferred-flush design in SURVEY.md §7 step 2. Conflicting-vote
(equivocation) evidence semantics are preserved for both paths.
"""

from __future__ import annotations

from dataclasses import dataclass

from cometbft_tpu.crypto import batch as crypto_batch
from cometbft_tpu.libs.bits import BitArray
from cometbft_tpu.types.basic import MAX_VOTES_COUNT, BlockID, BlockIDFlag, SignedMsgType
from cometbft_tpu.types.commit import Commit, CommitSig, ExtendedCommit, ExtendedCommitSig
from cometbft_tpu.types.validator import ValidatorSet
from cometbft_tpu.types.vote import Vote


# flush_pending per-vote statuses
FLUSH_ADDED = "added"        # signature verified, vote tallied
FLUSH_INVALID = "invalid"    # bad signature or extension signature
FLUSH_CONFLICT = "conflict"  # valid signature, rejected as equivocation


class ErrVoteConflictingVotes(Exception):
    """Equivocation detected — carries both votes (evidence material)."""

    def __init__(self, vote_a: Vote, vote_b: Vote):
        super().__init__(f"conflicting votes from validator {vote_b.validator_address.hex()}")
        self.vote_a = vote_a
        self.vote_b = vote_b


class ErrVoteInvalidSignature(Exception):
    pass


@dataclass
class _BlockVotes:
    """Votes for one particular block (vote_set.go:471-500)."""

    peer_maj23: bool
    bit_array: BitArray
    votes: list[Vote | None]
    sum: int

    @classmethod
    def new(cls, peer_maj23: bool, num_validators: int) -> "_BlockVotes":
        return cls(
            peer_maj23=peer_maj23,
            bit_array=BitArray(num_validators),
            votes=[None] * num_validators,
            sum=0,
        )

    def add_verified_vote(self, vote: Vote, voting_power: int) -> None:
        idx = vote.validator_index
        if self.votes[idx] is None:
            self.bit_array.set_index(idx, True)
            self.votes[idx] = vote
            self.sum += voting_power

    def get_by_index(self, idx: int) -> Vote | None:
        return self.votes[idx]


class VoteSet:
    """vote_set.go:55-100."""

    def __init__(
        self,
        chain_id: str,
        height: int,
        round_: int,
        signed_msg_type: SignedMsgType,
        val_set: ValidatorSet,
        extensions_enabled: bool = False,
        batch_flush_size: int = 128,
        auto_flush: bool = True,
    ):
        if height == 0:
            raise ValueError("cannot make VoteSet for height == 0, doesn't make sense")
        if len(val_set) > MAX_VOTES_COUNT:
            raise ValueError(f"validator set exceeds MaxVotesCount {MAX_VOTES_COUNT}")
        self.chain_id = chain_id
        self.height = height
        self.round_ = round_
        self.signed_msg_type = signed_msg_type
        self.extensions_enabled = extensions_enabled
        self.val_set = val_set
        self.votes_bit_array = BitArray(len(val_set))
        self.votes: list[Vote | None] = [None] * len(val_set)
        self.sum = 0
        self.maj23: BlockID | None = None
        self.votes_by_block: dict[bytes, _BlockVotes] = {}
        self.peer_maj23s: dict[str, BlockID] = {}
        # --- batch path state ---
        self.batch_flush_size = batch_flush_size
        # auto_flush=False hands flush control to the caller (consensus
        # needs the flush results to fire events / run threshold hooks)
        self.auto_flush = auto_flush
        self._pending: list[tuple[Vote, int]] = []  # (vote, voting_power)
        self._pending_by_key: dict[tuple[int, bytes], Vote] = {}
        self._speculative_sum = 0

    def size(self) -> int:
        return len(self.val_set)

    # ------------------------------------------------------ serial add path

    def add_vote(self, vote: Vote) -> bool:
        """Reference addVote (vote_set.go:157-231): full structural checks +
        serial signature verification + verified-tally update. Returns True
        if added; False for exact duplicates; raises on anything bad."""
        val, _ = self._check_structure(vote)
        existing = self._get_vote(vote.validator_index, vote.block_id.key())
        if existing is not None:
            if existing.signature == vote.signature:
                return False
            raise ValueError(
                f"non-deterministic signature: existing {existing}; new {vote}"
            )
        if self.extensions_enabled:
            if not vote.verify_vote_and_extension(self.chain_id, val.pub_key):
                raise ErrVoteInvalidSignature(f"failed to verify extended vote {vote}")
        else:
            if not vote.verify(self.chain_id, val.pub_key):
                raise ErrVoteInvalidSignature(f"failed to verify vote {vote}")
            if vote.extension or vote.extension_signature:
                raise ValueError("unexpected vote extension data present in vote")
        return self._add_verified_vote(vote, val.voting_power)

    # ------------------------------------------------------- batch add path

    def add_pending(self, vote: Vote) -> bool:
        """Stage a vote for batched verification. Cheap host-side checks
        only; consensus-visible state untouched. Returns True if staged
        (auto-flushes at quorum boundaries / batch size; see flush_pending)."""
        val, _ = self._check_structure(vote)
        if len(vote.signature) != 64:
            raise ErrVoteInvalidSignature(f"bad signature length {len(vote.signature)}")
        key = (vote.validator_index, vote.block_id.key())
        staged = self._pending_by_key.get(key)
        if staged is not None:
            if staged.signature == vote.signature:
                return False
            raise ValueError(
                f"non-deterministic signature: staged {staged}; new {vote}"
            )
        existing = self._get_vote(vote.validator_index, vote.block_id.key())
        if existing is not None:
            if existing.signature == vote.signature:
                return False
            raise ValueError(
                f"non-deterministic signature: existing {existing}; new {vote}"
            )
        if not self.extensions_enabled and (vote.extension or vote.extension_signature):
            raise ValueError("unexpected vote extension data present in vote")
        self._pending.append((vote, val.voting_power))
        self._pending_by_key[key] = vote
        if self.votes[vote.validator_index] is None:
            self._speculative_sum += val.voting_power
        if self.auto_flush and self.should_flush():
            self.flush_pending()
        return True

    def should_flush(self) -> bool:
        """True when flushing now is worthwhile: the staged batch is full,
        or the speculative (unverified) tally would cross the 2/3 quorum —
        the deferred-flush boundary that keeps 'never count an unverified
        vote' compatible with batching (SURVEY.md §7 step 2)."""
        if len(self._pending) >= self.batch_flush_size:
            return True
        # quorum boundary: the speculative (unverified) tally would cross
        # 2/3 — verifying now lets consensus observe the majority.
        quorum = self.val_set.total_voting_power() * 2 // 3 + 1
        return self.sum < quorum <= self.sum + self._speculative_sum

    def flush_pending(self) -> list[tuple[Vote, str]]:
        """Verify all staged votes in ONE device batch; fold the valid ones
        into the verified tally. Returns [(vote, status)] with status one
        of FLUSH_ADDED (verified + tallied), FLUSH_INVALID (bad
        signature/extension), FLUSH_CONFLICT (signature valid but rejected
        as an equivocation — distinct so callers can turn it into
        DuplicateVoteEvidence). Conflicting votes ALSO surface as
        ErrVoteConflictingVotes AFTER the tally is updated with everything
        non-conflicting (matching serial-path ordering)."""
        if not self._pending:
            return []
        pending, self._pending = self._pending, []
        self._pending_by_key.clear()
        self._speculative_sum = 0

        proposer = self.val_set.get_proposer()
        results: list[tuple[Vote, str]] = []
        batchable = len(pending) >= 2 and crypto_batch.supports_batch_verifier(
            proposer.pub_key if proposer else None
        )
        if batchable:
            bv = crypto_batch.create_batch_verifier(proposer.pub_key)
            for vote, _power in pending:
                _, val = self.val_set.get_by_index(vote.validator_index)
                bv.add(val.pub_key, vote.sign_bytes(self.chain_id), vote.signature)
            _, mask = bv.verify()
        else:
            mask = []
            for vote, _power in pending:
                _, val = self.val_set.get_by_index(vote.validator_index)
                mask.append(vote.verify(self.chain_id, val.pub_key))

        ext_bad: set[int] = set()
        if self.extensions_enabled:
            # Extension signatures ride a second batch over the same keys.
            ext_rows = []
            for i, (vote, _) in enumerate(pending):
                if not mask[i] or vote.block_id.is_nil():
                    continue
                if len(vote.extension_signature) != 64:
                    ext_bad.add(i)  # structurally invalid: fails without device trip
                    continue
                ext_rows.append((i, vote))
            if ext_rows:
                bv2 = crypto_batch.create_batch_verifier(proposer.pub_key)
                for _, vote in ext_rows:
                    _, val = self.val_set.get_by_index(vote.validator_index)
                    bv2.add(val.pub_key, vote.extension_sign_bytes(self.chain_id), vote.extension_signature)
                _, ext_mask = bv2.verify()
                for (i, _), ok in zip(ext_rows, ext_mask):
                    if not ok:
                        ext_bad.add(i)

        conflict: ErrVoteConflictingVotes | None = None
        conflicts: list[ErrVoteConflictingVotes] = []
        for i, (vote, power) in enumerate(pending):
            if not (bool(mask[i]) and i not in ext_bad):
                results.append((vote, FLUSH_INVALID))
                continue
            existing = self._get_vote(vote.validator_index, vote.block_id.key())
            if existing is not None and existing.signature == vote.signature:
                # landed via the serial path while staged: already tallied
                results.append((vote, FLUSH_ADDED))
                continue
            try:
                self._add_verified_vote(vote, power)
                results.append((vote, FLUSH_ADDED))
            except ErrVoteConflictingVotes as e:
                conflict = conflict or e
                conflicts.append(e)
                results.append((vote, FLUSH_CONFLICT))
        if conflict is not None:
            # The raise preserves serial-path parity; the full per-vote
            # outcome survives on the exception so callers can build
            # DuplicateVoteEvidence for EVERY equivocation in the flush,
            # not just the first pair.
            conflict.results = results
            conflict.conflicts = conflicts
            raise conflict
        return results

    # -------------------------------------------------------------- internals

    def _check_structure(self, vote: Vote):
        if vote is None:
            raise ValueError("nil vote")
        if vote.validator_index < 0:
            raise ValueError("index < 0: invalid validator index")
        if not vote.validator_address:
            raise ValueError("empty address: invalid validator address")
        if (
            vote.height != self.height
            or vote.round_ != self.round_
            or vote.type_ != self.signed_msg_type
        ):
            raise ValueError(
                f"expected {self.height}/{self.round_}/{self.signed_msg_type}, got "
                f"{vote.height}/{vote.round_}/{vote.type_}: unexpected step"
            )
        lookup_addr, val = self.val_set.get_by_index(vote.validator_index)
        if val is None:
            raise ValueError(
                f"cannot find validator {vote.validator_index} in valSet of size {self.size()}"
            )
        if vote.validator_address != lookup_addr:
            raise ValueError(
                f"vote.ValidatorAddress ({vote.validator_address.hex()}) does not match "
                f"address ({lookup_addr.hex()}) for vote.ValidatorIndex ({vote.validator_index})"
            )
        return val, lookup_addr

    def _get_vote(self, val_index: int, block_key: bytes) -> Vote | None:
        existing = self.votes[val_index]
        if existing is not None and existing.block_id.key() == block_key:
            return existing
        bv = self.votes_by_block.get(block_key)
        if bv is not None:
            return bv.get_by_index(val_index)
        return None

    def _add_verified_vote(self, vote: Vote, voting_power: int) -> bool:
        """vote_set.go:257-330 addVerifiedVote."""
        val_index = vote.validator_index
        block_key = vote.block_id.key()
        conflicting: Vote | None = None

        existing = self.votes[val_index]
        if existing is None:
            self.votes[val_index] = vote
            self.votes_bit_array.set_index(val_index, True)
            self.sum += voting_power
        else:
            if existing.block_id == vote.block_id:
                raise RuntimeError("_add_verified_vote does not expect duplicate votes")
            conflicting = existing
            # Replace vote if the maj23 block's vote (vote_set.go:284-291)
            if self.maj23 is not None and self.maj23.key() == block_key:
                self.votes[val_index] = vote
                self.votes_bit_array.set_index(val_index, True)

        votes_by_block = self.votes_by_block.get(block_key)
        if votes_by_block is not None:
            if conflicting is not None and not votes_by_block.peer_maj23:
                # ignore conflicting vote without peer maj23 (vote_set.go:297-301)
                raise ErrVoteConflictingVotes(conflicting, vote)
        else:
            if conflicting is not None:
                # peer claimed no maj23 for this block: ignore (vote_set.go:305-312)
                raise ErrVoteConflictingVotes(conflicting, vote)
            votes_by_block = _BlockVotes.new(False, self.size())
            self.votes_by_block[block_key] = votes_by_block

        old_sum = votes_by_block.sum
        quorum = self.val_set.total_voting_power() * 2 // 3 + 1
        votes_by_block.add_verified_vote(vote, voting_power)
        if old_sum < quorum <= votes_by_block.sum and self.maj23 is None:
            self.maj23 = vote.block_id
            # promote this block's votes to the main tracking (vote_set.go:326-330)
            for i, v in enumerate(votes_by_block.votes):
                if v is not None:
                    self.votes[i] = v
        if conflicting is not None:
            raise ErrVoteConflictingVotes(conflicting, vote)
        return True

    # ---------------------------------------------------------- peer maj23

    def set_peer_maj23(self, peer_id: str, block_id: BlockID) -> None:
        """vote_set.go:339-368: peer claims a +2/3 majority for block_id."""
        existing = self.peer_maj23s.get(peer_id)
        if existing is not None:
            if existing == block_id:
                return
            raise ValueError(
                f"setPeerMaj23: Received conflicting blockID from peer {peer_id}: "
                f"{existing} vs {block_id}"
            )
        self.peer_maj23s[peer_id] = block_id
        block_key = block_id.key()
        votes_by_block = self.votes_by_block.get(block_key)
        if votes_by_block is not None:
            votes_by_block.peer_maj23 = True
        else:
            self.votes_by_block[block_key] = _BlockVotes.new(True, self.size())

    # ------------------------------------------------------------- queries

    def bit_array(self) -> BitArray:
        return self.votes_bit_array.copy()

    def bit_array_by_block_id(self, block_id: BlockID) -> BitArray | None:
        bv = self.votes_by_block.get(block_id.key())
        return bv.bit_array.copy() if bv is not None else None

    def get_by_index(self, idx: int) -> Vote | None:
        return self.votes[idx]

    def get_by_address(self, address: bytes) -> Vote | None:
        idx, val = self.val_set.get_by_address(address)
        return self.votes[idx] if val is not None else None

    def has_two_thirds_majority(self) -> bool:
        return self.maj23 is not None

    def two_thirds_majority(self) -> tuple[BlockID | None, bool]:
        if self.maj23 is not None:
            return self.maj23, True
        return None, False

    def has_two_thirds_any(self) -> bool:
        return self.sum > self.val_set.total_voting_power() * 2 // 3

    def has_one_third_any(self) -> bool:
        return self.sum > self.val_set.total_voting_power() // 3

    def has_all(self) -> bool:
        return self.sum == self.val_set.total_voting_power()

    def is_commit(self) -> bool:
        return self.signed_msg_type == SignedMsgType.PRECOMMIT and self.maj23 is not None

    # -------------------------------------------------------------- commit

    def make_commit(self) -> Commit:
        """vote_set.go MakeCommit (plain, pre-extension)."""
        if self.signed_msg_type != SignedMsgType.PRECOMMIT:
            raise ValueError("cannot MakeCommit() unless VoteSet.Type is PRECOMMIT")
        if self.maj23 is None:
            raise ValueError("cannot MakeCommit() unless a blockhash has +2/3")
        sigs = []
        for i, v in enumerate(self.votes):
            sigs.append(self._commit_sig_for(v, i))
        return Commit(
            height=self.height, round_=self.round_, block_id=self.maj23, signatures=sigs
        )

    def make_extended_commit(self) -> ExtendedCommit:
        if self.signed_msg_type != SignedMsgType.PRECOMMIT:
            raise ValueError("cannot MakeExtendedCommit() unless VoteSet.Type is PRECOMMIT")
        if self.maj23 is None:
            raise ValueError("cannot MakeExtendedCommit() unless a blockhash has +2/3")
        esigs = []
        for i, v in enumerate(self.votes):
            cs = self._commit_sig_for(v, i)
            esigs.append(
                ExtendedCommitSig(
                    commit_sig=cs,
                    extension=v.extension if v is not None and cs.for_block() else b"",
                    extension_signature=(
                        v.extension_signature if v is not None and cs.for_block() else b""
                    ),
                )
            )
        return ExtendedCommit(
            height=self.height,
            round_=self.round_,
            block_id=self.maj23,
            extended_signatures=esigs,
        )

    def _commit_sig_for(self, v: Vote | None, idx: int) -> CommitSig:
        if v is None:
            return CommitSig.absent()
        if v.block_id == self.maj23:
            flag = BlockIDFlag.COMMIT
        elif v.block_id.is_nil():
            flag = BlockIDFlag.NIL
        else:
            # Vote for a different block: excluded as ABSENT — its signature
            # is over that other BlockID and would fail reconstruction
            # (reference: vote_set.go MakeExtendedCommit:652-655).
            return CommitSig.absent()
        return CommitSig(
            block_id_flag=flag,
            validator_address=v.validator_address,
            timestamp=v.timestamp,
            signature=v.signature,
        )


def commit_to_vote_set(chain_id: str, commit: Commit, val_set: ValidatorSet) -> VoteSet:
    """Rebuild the precommit VoteSet a Commit was distilled from, verifying
    every signature (types/vote_set.go CommitToVoteSet). Used on restart to
    reconstruct LastCommit from the block store's seen commit."""
    vote_set = VoteSet(
        chain_id, commit.height, commit.round_, SignedMsgType.PRECOMMIT, val_set
    )
    for idx, cs in enumerate(commit.signatures):
        if not cs.for_block() and not cs.signature:
            continue  # OK, absent — no vote to reconstruct
        added = vote_set.add_vote(commit.get_vote(idx))
        if not added:
            raise RuntimeError(f"failed to reconstruct vote {idx} from commit")
    return vote_set


def extended_commit_to_vote_set(
    chain_id: str, ext_commit: ExtendedCommit, val_set: ValidatorSet
) -> VoteSet:
    """types/vote_set.go ExtendedCommit.ToExtendedVoteSet: like
    commit_to_vote_set but carrying (and verifying) vote extensions."""
    vote_set = VoteSet(
        chain_id,
        ext_commit.height,
        ext_commit.round_,
        SignedMsgType.PRECOMMIT,
        val_set,
        extensions_enabled=True,
    )
    commit = ext_commit.to_commit()  # hoisted: get_extended_vote rebuilds it per call
    for idx, ecs in enumerate(ext_commit.extended_signatures):
        if not ecs.commit_sig.for_block() and not ecs.commit_sig.signature:
            continue
        vote = commit.get_vote(idx)
        vote.extension = ecs.extension
        vote.extension_signature = ecs.extension_signature
        added = vote_set.add_vote(vote)
        if not added:
            raise RuntimeError(f"failed to reconstruct extended vote {idx}")
    return vote_set
