"""Validator and ValidatorSet with proposer-priority rotation.

Reference: types/validator.go, types/validator_set.go. The rotation
algorithm (a-priori deterministic weighted round-robin with priority
centering and rescaling) is consensus-critical: every node must compute the
identical proposer for (height, round), so the arithmetic here mirrors the
reference exactly — including int64 clipping semantics
(validator_set.go:114-250) — implemented over Python ints with explicit
clamps.
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace

from cometbft_tpu import crypto
from cometbft_tpu.crypto import merkle
from cometbft_tpu.utils import protobuf as pb

INT64_MAX = (1 << 63) - 1
INT64_MIN = -(1 << 63)
# reference: types/validator_set.go:25
MAX_TOTAL_VOTING_POWER = INT64_MAX // 8
# reference: types/validator_set.go:30
PRIORITY_WINDOW_SIZE_FACTOR = 2


def _clip(v: int) -> int:
    return max(INT64_MIN, min(INT64_MAX, v))


@dataclass
class Validator:
    """types/validator.go:13-20."""

    address: bytes
    pub_key: crypto.PubKey
    voting_power: int
    proposer_priority: int = 0

    @classmethod
    def new(cls, pub_key: crypto.PubKey, voting_power: int) -> "Validator":
        return cls(
            address=pub_key.address(),
            pub_key=pub_key,
            voting_power=voting_power,
            proposer_priority=0,
        )

    def copy(self) -> "Validator":
        return replace(self)

    def validate_basic(self) -> None:
        if self.pub_key is None:
            raise ValueError("validator does not have a public key")
        if self.voting_power < 0:
            raise ValueError("validator has negative voting power")
        if len(self.address) != crypto.ADDRESS_SIZE:
            raise ValueError("validator address is the wrong size")

    def compare_proposer_priority(self, other: "Validator") -> int:
        """Higher priority wins; tie-break by lower address
        (validator_set.go CompareProposerPriority)."""
        if self.proposer_priority > other.proposer_priority:
            return -1
        if self.proposer_priority < other.proposer_priority:
            return 1
        if self.address < other.address:
            return -1
        if self.address > other.address:
            return 1
        raise ValueError("cannot compare identical validators")

    def bytes_(self) -> bytes:
        """SimpleValidator proto: pub_key=1 (crypto.PublicKey oneof),
        voting_power=2 — the valset-hash leaf (types/validator.go:117-133)."""
        pk = pub_key_to_proto(self.pub_key)
        w = pb.Writer()
        w.message(1, pk)
        w.varint_i64(2, self.voting_power)
        return w.output()

    def to_proto(self) -> bytes:
        """tendermint.types.Validator: address=1, pub_key=2, voting_power=3,
        proposer_priority=4 (types/validator.go ToProto)."""
        w = pb.Writer()
        w.bytes(1, self.address)
        w.message(2, pub_key_to_proto(self.pub_key), always=True)
        w.varint_i64(3, self.voting_power)
        w.varint_i64(4, self.proposer_priority)
        return w.output()

    @classmethod
    def from_proto(cls, data: bytes) -> "Validator":
        r = pb.Reader(data)
        address = b""
        pub_key = None
        power = 0
        priority = 0
        while not r.at_end():
            f, w = r.read_tag()
            if f == 1:
                address = r.read_bytes()
            elif f == 2:
                pub_key = pub_key_from_proto(r.read_bytes())
            elif f == 3:
                power = r.read_varint_i64()
            elif f == 4:
                priority = r.read_varint_i64()
            else:
                r.skip(w)
        if pub_key is None:
            raise ValueError("Validator proto missing pub_key")
        return cls(
            address=address or pub_key.address(),
            pub_key=pub_key,
            voting_power=power,
            proposer_priority=priority,
        )


def pub_key_to_proto(pub_key: crypto.PubKey) -> bytes:
    """crypto.PublicKey oneof: ed25519=1 bytes, secp256k1=2 bytes
    (proto/tendermint/crypto/keys.proto)."""
    field_num = {"ed25519": 1, "secp256k1": 2, "sr25519": 3,
                 "bls12381": 4}.get(pub_key.type_())
    if field_num is None:
        raise ValueError(f"unsupported pubkey type {pub_key.type_()}")
    return pb.Writer().bytes(field_num, pub_key.bytes_(), always=True).output()


def pub_key_from_proto(data: bytes) -> crypto.PubKey:
    from cometbft_tpu.crypto import ed25519

    r = pb.Reader(data)
    while not r.at_end():
        f, w = r.read_tag()
        if f == 1:
            return ed25519.PubKey(r.read_bytes())
        if f == 2:
            from cometbft_tpu.crypto import secp256k1

            return secp256k1.PubKey(r.read_bytes())
        if f == 3:
            from cometbft_tpu.crypto import sr25519

            return sr25519.PubKey(r.read_bytes())
        if f == 4:
            from cometbft_tpu.crypto import bls12381

            return bls12381.PubKey(r.read_bytes())
        r.skip(w)
    raise ValueError("empty/unsupported PublicKey proto")


class ValidatorSet:
    """types/validator_set.go:55-66. Validators sorted by address; proposer
    tracked explicitly and rotated by priority."""

    def __init__(self, validators: list[Validator]):
        self.validators: list[Validator] = sorted(
            (v.copy() for v in validators), key=lambda v: v.address
        )
        self.proposer: Validator | None = None
        self._total_voting_power: int | None = None
        if self.validators:
            self._update_total_voting_power()
            self.increment_proposer_priority(1)

    # ---------------------------------------------------------------- basics

    def is_nil_or_empty(self) -> bool:
        return not self.validators

    def __len__(self) -> int:
        return len(self.validators)

    def copy(self) -> "ValidatorSet":
        new = ValidatorSet.__new__(ValidatorSet)
        new.validators = [v.copy() for v in self.validators]
        new.proposer = self.proposer.copy() if self.proposer else None
        new._total_voting_power = self._total_voting_power
        return new

    def _update_total_voting_power(self) -> None:
        total = 0
        for v in self.validators:
            total += v.voting_power
            if total > MAX_TOTAL_VOTING_POWER:
                raise ValueError(
                    f"total voting power cannot exceed {MAX_TOTAL_VOTING_POWER}"
                )
        self._total_voting_power = total

    def total_voting_power(self) -> int:
        if self._total_voting_power is None:
            self._update_total_voting_power()
        return self._total_voting_power

    def has_address(self, address: bytes) -> bool:
        return any(v.address == address for v in self.validators)

    def get_by_address(self, address: bytes) -> tuple[int, Validator | None]:
        for i, v in enumerate(self.validators):
            if v.address == address:
                return i, v.copy()
        return -1, None

    def get_by_index(self, index: int) -> tuple[bytes, Validator | None]:
        if index < 0 or index >= len(self.validators):
            return b"", None
        v = self.validators[index]
        return v.address, v.copy()

    # ------------------------------------------------------------- proposer

    def get_proposer(self) -> Validator | None:
        if not self.validators:
            return None
        if self.proposer is None:
            self.proposer = self._find_proposer()
        return self.proposer.copy()

    def _find_proposer(self) -> Validator:
        best = None
        for v in self.validators:
            if best is None or v.compare_proposer_priority(best) < 0:
                best = v
        return best

    def increment_proposer_priority(self, times: int) -> None:
        """validator_set.go:114-136."""
        if self.is_nil_or_empty():
            raise ValueError("empty validator set")
        if times <= 0:
            raise ValueError("cannot call IncrementProposerPriority with non-positive times")
        diff_max = PRIORITY_WINDOW_SIZE_FACTOR * self.total_voting_power()
        self.rescale_priorities(diff_max)
        self._shift_by_avg_proposer_priority()
        proposer = None
        for _ in range(times):
            proposer = self._increment_proposer_priority()
        self.proposer = proposer

    def rescale_priorities(self, diff_max: int) -> None:
        """validator_set.go:141-162: divide by ceil(diff/diffMax) when the
        priority span exceeds diffMax. Go integer division truncates toward
        zero — mirror that, not Python floor."""
        if diff_max <= 0:
            return
        diff = self._max_min_priority_diff()
        ratio = (diff + diff_max - 1) // diff_max
        if diff > diff_max:
            for v in self.validators:
                q = abs(v.proposer_priority) // ratio
                v.proposer_priority = q if v.proposer_priority >= 0 else -q

    def _max_min_priority_diff(self) -> int:
        prios = [v.proposer_priority for v in self.validators]
        return abs(max(prios) - min(prios))

    def _increment_proposer_priority(self) -> Validator:
        for v in self.validators:
            v.proposer_priority = _clip(v.proposer_priority + v.voting_power)
        mostest = self._find_proposer()
        mostest.proposer_priority = _clip(
            mostest.proposer_priority - self.total_voting_power()
        )
        return mostest

    def _shift_by_avg_proposer_priority(self) -> None:
        n = len(self.validators)
        # Go big.Int Div: Euclidean-style? No — big.Int.Div with positive
        # divisor floors toward -inf for negative dividends, same as Python.
        avg = sum(v.proposer_priority for v in self.validators) // n
        for v in self.validators:
            v.proposer_priority = _clip(v.proposer_priority - avg)

    # ---------------------------------------------------------------- hash

    def hash(self) -> bytes:
        """Merkle root of SimpleValidator leaves (validator_set.go:347-353)."""
        return merkle.hash_from_byte_slices([v.bytes_() for v in self.validators])

    # -------------------------------------------------------------- updates

    def update_with_change_set(self, changes: list[Validator]) -> None:
        """Apply ABCI ValidatorUpdates (validator_set.go:502-576 semantics):
        power 0 = removal; new addresses added; existing updated. Priorities
        of new validators start at -1.125 * total power (so they don't
        immediately propose); then recenter/rescale."""
        if not changes:
            return
        seen: set[bytes] = set()
        for c in changes:
            if c.address in seen:
                raise ValueError(f"duplicate entry {c.address.hex()} in changes")
            seen.add(c.address)
            if c.voting_power < 0:
                raise ValueError("voting power can't be negative")

        removals = {c.address for c in changes if c.voting_power == 0}
        updates = [c for c in changes if c.voting_power > 0]

        for addr in removals:
            if not self.has_address(addr):
                raise ValueError(f"failed to find validator {addr.hex()} to remove")

        by_addr = {v.address: v for v in self.validators}
        # Total voting power after updates but BEFORE removals — the base
        # for both the cap check and new-validator priorities
        # (validator_set.go:490,618-624 tvpAfterUpdatesBeforeRemovals;
        # excluding removals here would permanently diverge proposer
        # rotation from the reference for mixed add+remove change sets).
        upd_by_addr = {u.address: u for u in updates}
        new_total = 0
        for v in self.validators:
            upd = upd_by_addr.get(v.address)
            new_total += upd.voting_power if upd else v.voting_power
        for u in updates:
            if u.address not in by_addr:
                new_total += u.voting_power
        if new_total > MAX_TOTAL_VOTING_POWER:
            raise ValueError("total voting power would exceed maximum")

        for u in updates:
            existing = by_addr.get(u.address)
            if existing is not None:
                existing.voting_power = u.voting_power
                existing.pub_key = u.pub_key
            else:
                nv = u.copy()
                # validator_set.go:316: new validators get -(total + total/8)
                nv.proposer_priority = -(new_total + (new_total >> 3))
                self.validators.append(nv)
        self.validators = [v for v in self.validators if v.address not in removals]
        self.validators.sort(key=lambda v: v.address)
        self._total_voting_power = None
        self._update_total_voting_power()
        if self.validators:
            self.rescale_priorities(PRIORITY_WINDOW_SIZE_FACTOR * self.total_voting_power())
            self._shift_by_avg_proposer_priority()
            self.proposer = self._find_proposer()

    def validate_basic(self) -> None:
        if self.is_nil_or_empty():
            raise ValueError("validator set is nil or empty")
        for v in self.validators:
            v.validate_basic()
        if self.proposer is not None:
            self.proposer.validate_basic()
            if not self.has_address(self.proposer.address):
                raise ValueError("proposer not in validator set")

    def __iter__(self):
        return iter(self.validators)

    # ---------------------------------------------------------------- wire

    def to_proto(self) -> bytes:
        """tendermint.types.ValidatorSet: validators=1, proposer=2,
        total_voting_power=3 (types/validator_set.go ToProto)."""
        w = pb.Writer()
        for v in self.validators:
            w.message(1, v.to_proto(), always=True)
        if self.proposer is not None:
            w.message(2, self.proposer.to_proto(), always=True)
        w.varint_i64(3, self.total_voting_power())
        return w.output()

    @classmethod
    def from_proto(cls, data: bytes) -> "ValidatorSet":
        r = pb.Reader(data)
        vals: list[Validator] = []
        proposer: Validator | None = None
        while not r.at_end():
            f, w = r.read_tag()
            if f == 1:
                vals.append(Validator.from_proto(r.read_bytes()))
            elif f == 2:
                proposer = Validator.from_proto(r.read_bytes())
            else:
                r.skip(w)
        vs = cls.__new__(cls)
        vs.validators = sorted(vals, key=lambda v: v.address)
        vs.proposer = proposer
        vs._total_voting_power = None
        if vs.validators:
            vs._update_total_voting_power()
        return vs
