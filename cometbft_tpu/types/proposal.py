"""Proposal (reference: types/proposal.go)."""

from __future__ import annotations

from dataclasses import dataclass, field

from cometbft_tpu import crypto
from cometbft_tpu.types import canonical
from cometbft_tpu.types.basic import BlockID, SignedMsgType
from cometbft_tpu.utils import cmttime
from cometbft_tpu.utils import protobuf as pb


@dataclass
class Proposal:
    height: int
    round_: int
    pol_round: int  # -1 when no proof-of-lock
    block_id: BlockID
    timestamp: cmttime.Timestamp
    signature: bytes = b""

    def sign_bytes(self, chain_id: str) -> bytes:
        return canonical.proposal_sign_bytes(
            chain_id, self.height, self.round_, self.pol_round, self.block_id, self.timestamp
        )

    def verify(self, chain_id: str, pub_key: crypto.PubKey) -> bool:
        return pub_key.verify_signature(self.sign_bytes(chain_id), self.signature)

    def validate_basic(self) -> None:
        """proposal.go ValidateBasic."""
        if self.height <= 0:
            raise ValueError("non-positive Height")
        if self.round_ < 0:
            raise ValueError("negative Round")
        if self.pol_round < -1 or self.pol_round >= self.round_:
            raise ValueError("POLRound must be -1 or in [0, round)")
        self.block_id.validate_basic()
        if not self.block_id.is_complete():
            raise ValueError(f"expected a complete, non-empty BlockID, got: {self.block_id}")
        if not self.signature:
            raise ValueError("signature is missing")
        if len(self.signature) > crypto.MAX_SIGNATURE_SIZE:
            raise ValueError("signature is too big")

    def to_proto(self) -> bytes:
        w = pb.Writer()
        w.uvarint(1, int(SignedMsgType.PROPOSAL))
        w.varint_i64(2, self.height)
        w.varint_i64(3, self.round_)
        w.varint_i64(4, self.pol_round & ((1 << 64) - 1) if self.pol_round < 0 else self.pol_round)
        w.message(5, self.block_id.to_proto(), always=True)
        w.message(6, pb.timestamp_bytes(self.timestamp.seconds, self.timestamp.nanos), always=True)
        w.bytes(7, self.signature)
        return w.output()

    @classmethod
    def from_proto(cls, data: bytes) -> "Proposal":
        r = pb.Reader(data)
        p = cls(
            height=0,
            round_=0,
            pol_round=0,
            block_id=BlockID(),
            timestamp=cmttime.Timestamp.zero(),
        )
        while not r.at_end():
            f, w = r.read_tag()
            if f == 2:
                p.height = r.read_varint_i64()
            elif f == 3:
                p.round_ = r.read_varint_i64()
            elif f == 4:
                p.pol_round = r.read_varint_i64()
            elif f == 5:
                p.block_id = BlockID.from_proto(r.read_bytes())
            elif f == 6:
                secs, nanos = r.read_timestamp()
                p.timestamp = cmttime.Timestamp(secs, nanos)
            elif f == 7:
                p.signature = r.read_bytes()
            else:
                r.skip(w)
        return p
