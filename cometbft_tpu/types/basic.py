"""Primitive domain types shared across the types layer.

Reference seams: SignedMsgType (proto/tendermint/types/types.proto),
BlockIDFlag (types/block.go:574-583), BlockID/PartSetHeader
(types/block.go, proto layout types.proto:27-42), size limits
(types/vote_set.go:17).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field

from cometbft_tpu.crypto import tmhash
from cometbft_tpu.utils import protobuf as pb

# reference: types/vote_set.go:17 — hard cap on votes per set.
MAX_VOTES_COUNT = 10000


class SignedMsgType(enum.IntEnum):
    """proto/tendermint/types/types.proto SignedMsgType."""

    UNKNOWN = 0
    PREVOTE = 1
    PRECOMMIT = 2
    PROPOSAL = 32


class BlockIDFlag(enum.IntEnum):
    """types/block.go:578-583."""

    ABSENT = 1
    COMMIT = 2
    NIL = 3


@dataclass(frozen=True)
class PartSetHeader:
    total: int = 0
    hash: bytes = b""

    def is_zero(self) -> bool:
        return self.total == 0 and len(self.hash) == 0

    def validate_basic(self) -> None:
        if self.total < 0:
            raise ValueError("negative Total")
        if self.hash and len(self.hash) != tmhash.SIZE:
            raise ValueError(f"wrong PartSetHeader hash size {len(self.hash)}")

    def to_proto(self) -> bytes:
        return pb.Writer().uvarint(1, self.total).bytes(2, self.hash).output()

    @classmethod
    def from_proto(cls, data: bytes) -> "PartSetHeader":
        r = pb.Reader(data)
        total, h = 0, b""
        while not r.at_end():
            f, w = r.read_tag()
            if f == 1:
                total = r.read_uvarint()
            elif f == 2:
                h = r.read_bytes()
            else:
                r.skip(w)
        return cls(total=total, hash=h)


@dataclass(frozen=True)
class BlockID:
    hash: bytes = b""
    part_set_header: PartSetHeader = field(default_factory=PartSetHeader)

    def is_nil(self) -> bool:
        """reference: types/block.go BlockID.IsNil — zero value = 'nil vote'."""
        return len(self.hash) == 0 and self.part_set_header.is_zero()

    def is_complete(self) -> bool:
        return (
            len(self.hash) == tmhash.SIZE
            and self.part_set_header.total > 0
            and len(self.part_set_header.hash) == tmhash.SIZE
        )

    def validate_basic(self) -> None:
        if self.hash and len(self.hash) != tmhash.SIZE:
            raise ValueError(f"wrong BlockID hash size {len(self.hash)}")
        self.part_set_header.validate_basic()

    def key(self) -> bytes:
        """Map key: hash || psh proto (reference: types/block.go BlockID.Key)."""
        return self.hash + self.part_set_header.to_proto()

    def to_proto(self) -> bytes:
        """types.proto BlockID: hash=1 bytes, part_set_header=2 non-nullable."""
        w = pb.Writer()
        w.bytes(1, self.hash)
        w.message(2, self.part_set_header.to_proto(), always=True)
        return w.output()

    @classmethod
    def from_proto(cls, data: bytes) -> "BlockID":
        r = pb.Reader(data)
        h, psh = b"", PartSetHeader()
        while not r.at_end():
            f, w = r.read_tag()
            if f == 1:
                h = r.read_bytes()
            elif f == 2:
                psh = PartSetHeader.from_proto(r.read_bytes())
            else:
                r.skip(w)
        return cls(hash=h, part_set_header=psh)

    def __str__(self) -> str:
        return f"{self.hash.hex()[:12]}:{self.part_set_header.total}"
