"""PartSet — block serialization into gossip-sized merkle-proven parts.

Reference: types/part_set.go. Blocks travel the consensus Data channel as
64 kB parts (BlockPartSizeBytes, types/params.go) with per-part merkle
proofs against the PartSetHeader root that the proposal commits to.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from cometbft_tpu.crypto import merkle
from cometbft_tpu.libs.bits import BitArray
from cometbft_tpu.types.basic import PartSetHeader
from cometbft_tpu.utils import protobuf as pb

BLOCK_PART_SIZE_BYTES = 65536  # types/params.go BlockPartSizeBytes


class ErrPartSetUnexpectedIndex(Exception):
    pass


class ErrPartSetInvalidProof(Exception):
    pass


@dataclass
class Part:
    index: int
    bytes_: bytes
    proof: merkle.Proof

    def validate_basic(self) -> None:
        if self.index < 0:
            raise ValueError("negative Index")
        if len(self.bytes_) > BLOCK_PART_SIZE_BYTES:
            raise ValueError(f"part bytes exceed maximum {BLOCK_PART_SIZE_BYTES}")
        if self.proof.index != self.index or len(self.proof.leaf_hash) != 32:
            raise ValueError("wrong proof")

    def to_proto(self) -> bytes:
        proof_w = pb.Writer()
        proof_w.varint_i64(1, self.proof.total)
        proof_w.varint_i64(2, self.proof.index)
        proof_w.bytes(3, self.proof.leaf_hash)
        for aunt in self.proof.aunts:
            proof_w.bytes(4, aunt, always=True)
        w = pb.Writer()
        w.uvarint(1, self.index)
        w.bytes(2, self.bytes_)
        w.message(3, proof_w.output(), always=True)
        return w.output()

    @classmethod
    def from_proto(cls, data: bytes) -> "Part":
        r = pb.Reader(data)
        index = 0
        body = b""
        proof = merkle.Proof(total=0, index=0, leaf_hash=b"")
        while not r.at_end():
            f, w = r.read_tag()
            if f == 1:
                index = r.read_uvarint()
            elif f == 2:
                body = r.read_bytes()
            elif f == 3:
                pr = r.read_message()
                total = pidx = 0
                leaf = b""
                aunts: list[bytes] = []
                while not pr.at_end():
                    pf, pw = pr.read_tag()
                    if pf == 1:
                        total = pr.read_varint_i64()
                    elif pf == 2:
                        pidx = pr.read_varint_i64()
                    elif pf == 3:
                        leaf = pr.read_bytes()
                    elif pf == 4:
                        aunts.append(pr.read_bytes())
                    else:
                        pr.skip(pw)
                proof = merkle.Proof(total=total, index=pidx, leaf_hash=leaf, aunts=aunts)
            else:
                r.skip(w)
        return cls(index=index, bytes_=body, proof=proof)


class PartSet:
    """types/part_set.go:129-292. Either built complete from data (proposer
    side) or assembled part-by-part with proof verification (receiver)."""

    def __init__(self, total: int, header_hash: bytes):
        self.total = total
        self.hash = header_hash
        self.parts: list[Part | None] = [None] * total
        self.parts_bit_array = BitArray(total)
        self.count = 0
        self.byte_size = 0

    @classmethod
    def from_data(cls, data: bytes, part_size: int = BLOCK_PART_SIZE_BYTES) -> "PartSet":
        """Split + build merkle proofs (part_set.go NewPartSetFromData)."""
        chunks = [data[i : i + part_size] for i in range(0, len(data), part_size)] or [b""]
        root, proofs = merkle.proofs_from_byte_slices(chunks)
        ps = cls(total=len(chunks), header_hash=root)
        for i, chunk in enumerate(chunks):
            part = Part(index=i, bytes_=chunk, proof=proofs[i])
            ps.parts[i] = part
            ps.parts_bit_array.set_index(i, True)
            ps.count += 1
            ps.byte_size += len(chunk)
        return ps

    @classmethod
    def from_header(cls, header: PartSetHeader) -> "PartSet":
        return cls(total=header.total, header_hash=header.hash)

    def header(self) -> PartSetHeader:
        return PartSetHeader(total=self.total, hash=self.hash)

    def has_header(self, header: PartSetHeader) -> bool:
        return self.header() == header

    def add_part(self, part: Part) -> bool:
        """part_set.go AddPart: False for duplicates; raises on bad
        index/proof."""
        if part.index >= self.total:
            raise ErrPartSetUnexpectedIndex(f"index {part.index} >= total {self.total}")
        if self.parts[part.index] is not None:
            return False
        if part.proof.total != self.total:
            raise ErrPartSetInvalidProof("proof total mismatch")
        if not part.proof.verify(self.hash, part.bytes_):
            raise ErrPartSetInvalidProof(f"invalid proof for part {part.index}")
        self.parts[part.index] = part
        self.parts_bit_array.set_index(part.index, True)
        self.count += 1
        self.byte_size += len(part.bytes_)
        return True

    def get_part(self, index: int) -> Part | None:
        return self.parts[index]

    def is_complete(self) -> bool:
        return self.count == self.total

    def get_reader(self) -> bytes:
        """Reassembled payload (only when complete)."""
        if not self.is_complete():
            raise ValueError("cannot read incomplete PartSet")
        return b"".join(p.bytes_ for p in self.parts)  # type: ignore[union-attr]

    def bit_array(self) -> BitArray:
        return self.parts_bit_array.copy()
