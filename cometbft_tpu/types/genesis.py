"""GenesisDoc (reference: types/genesis.go) — JSON-serialized chain origin."""

from __future__ import annotations

import base64
import hashlib
import json
from dataclasses import dataclass, field

from cometbft_tpu import crypto
from cometbft_tpu.crypto import bls12381, ed25519
from cometbft_tpu.types.params import ConsensusParams, default_consensus_params
from cometbft_tpu.types.validator import Validator, ValidatorSet
from cometbft_tpu.utils import cmttime

MAX_CHAIN_ID_LEN = 50

# JSON amino-style type tags per key scheme (genesis + priv_validator_key
# share the same registry; see privval/file_pv.py).
PUB_KEY_JSON_TYPES = {
    ed25519.KEY_TYPE: "tendermint/PubKeyEd25519",
    bls12381.KEY_TYPE: "cometbft/PubKeyBls12_381",
}
_PUB_KEY_DECODERS = {
    "tendermint/PubKeyEd25519": ed25519.PubKey,
    "cometbft/PubKeyBls12_381": bls12381.PubKey,
}


@dataclass
class GenesisValidator:
    address: bytes
    pub_key: crypto.PubKey
    power: int
    name: str = ""


@dataclass
class GenesisDoc:
    genesis_time: cmttime.Timestamp
    chain_id: str
    initial_height: int = 1
    consensus_params: ConsensusParams = field(default_factory=default_consensus_params)
    validators: list[GenesisValidator] = field(default_factory=list)
    app_hash: bytes = b""
    app_state: bytes = b"{}"

    def validator_set(self) -> ValidatorSet:
        return ValidatorSet(
            [Validator.new(v.pub_key, v.power) for v in self.validators]
        )

    def validate_and_complete(self) -> None:
        """genesis.go ValidateAndComplete."""
        if not self.chain_id:
            raise ValueError("genesis doc must include non-empty chain_id")
        if len(self.chain_id) > MAX_CHAIN_ID_LEN:
            raise ValueError(f"chain_id in genesis doc is too long (max: {MAX_CHAIN_ID_LEN})")
        if self.initial_height < 0:
            raise ValueError("initial_height cannot be negative")
        if self.initial_height == 0:
            self.initial_height = 1
        self.consensus_params.validate_basic()
        for i, v in enumerate(self.validators):
            if v.power == 0:
                raise ValueError(f"genesis file cannot contain validators with no voting power: {v}")
            if v.address and v.pub_key.address() != v.address:
                raise ValueError(f"incorrect address for validator {i}")
            if not v.address:
                v.address = v.pub_key.address()
        if self.genesis_time.is_zero():
            self.genesis_time = cmttime.now()

    def hash(self) -> bytes:
        return hashlib.sha256(self.to_json().encode()).digest()

    def to_json(self) -> str:
        return json.dumps(
            {
                "genesis_time": self.genesis_time.rfc3339(),
                "chain_id": self.chain_id,
                "initial_height": str(self.initial_height),
                "consensus_params": {
                    "block": {
                        "max_bytes": str(self.consensus_params.block.max_bytes),
                        "max_gas": str(self.consensus_params.block.max_gas),
                    },
                    "evidence": {
                        "max_age_num_blocks": str(self.consensus_params.evidence.max_age_num_blocks),
                        "max_age_duration": str(self.consensus_params.evidence.max_age_duration_ns),
                        "max_bytes": str(self.consensus_params.evidence.max_bytes),
                    },
                    "validator": {
                        "pub_key_types": self.consensus_params.validator.pub_key_types
                    },
                    "version": {"app": str(self.consensus_params.version.app)},
                    "abci": {
                        "vote_extensions_enable_height": str(
                            self.consensus_params.abci.vote_extensions_enable_height
                        )
                    },
                },
                "validators": [
                    {
                        "address": v.address.hex().upper(),
                        "pub_key": {
                            "type": PUB_KEY_JSON_TYPES.get(
                                v.pub_key.type_(), "tendermint/PubKeyEd25519"
                            ),
                            "value": base64.b64encode(v.pub_key.bytes_()).decode(),
                        },
                        "power": str(v.power),
                        "name": v.name,
                    }
                    for v in self.validators
                ],
                "app_hash": self.app_hash.hex().upper(),
                "app_state": json.loads(self.app_state.decode() or "{}"),
            },
            indent=2,
            sort_keys=False,
        )

    @classmethod
    def from_json(cls, raw: str | bytes) -> "GenesisDoc":
        d = json.loads(raw)
        cp = default_consensus_params()
        if "consensus_params" in d and d["consensus_params"]:
            cpd = d["consensus_params"]
            if "block" in cpd:
                cp.block.max_bytes = int(cpd["block"].get("max_bytes", cp.block.max_bytes))
                cp.block.max_gas = int(cpd["block"].get("max_gas", cp.block.max_gas))
            if "evidence" in cpd:
                cp.evidence.max_age_num_blocks = int(
                    cpd["evidence"].get("max_age_num_blocks", cp.evidence.max_age_num_blocks)
                )
                cp.evidence.max_age_duration_ns = int(
                    cpd["evidence"].get("max_age_duration", cp.evidence.max_age_duration_ns)
                )
                cp.evidence.max_bytes = int(cpd["evidence"].get("max_bytes", cp.evidence.max_bytes))
            if "validator" in cpd:
                cp.validator.pub_key_types = list(
                    cpd["validator"].get("pub_key_types", cp.validator.pub_key_types)
                )
            if "abci" in cpd:
                cp.abci.vote_extensions_enable_height = int(
                    cpd["abci"].get("vote_extensions_enable_height", 0)
                )
        validators = []
        for vd in d.get("validators", []):
            ctor = _PUB_KEY_DECODERS.get(
                vd["pub_key"].get("type", "tendermint/PubKeyEd25519"), ed25519.PubKey
            )
            pub = ctor(base64.b64decode(vd["pub_key"]["value"]))
            validators.append(
                GenesisValidator(
                    address=bytes.fromhex(vd["address"]) if vd.get("address") else pub.address(),
                    pub_key=pub,
                    power=int(vd["power"]),
                    name=vd.get("name", ""),
                )
            )
        ts = cmttime.Timestamp.zero()
        if d.get("genesis_time"):
            # RFC3339 parse (nanosecond-truncating)
            from datetime import datetime

            raw_t = d["genesis_time"].replace("Z", "+00:00")
            frac_ns = 0
            if "." in raw_t:
                base_part, rest = raw_t.split(".", 1)
                frac, tz = rest[:-6], rest[-6:]
                frac_ns = int(frac.ljust(9, "0")[:9])
                raw_t = base_part + tz
            dt = datetime.fromisoformat(raw_t)
            ts = cmttime.Timestamp(int(dt.timestamp()), frac_ns)
        doc = cls(
            genesis_time=ts,
            chain_id=d["chain_id"],
            initial_height=int(d.get("initial_height", 1)),
            consensus_params=cp,
            validators=validators,
            app_hash=bytes.fromhex(d.get("app_hash", "")),
            app_state=json.dumps(d.get("app_state", {})).encode(),
        )
        doc.validate_and_complete()
        return doc
