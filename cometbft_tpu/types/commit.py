"""Commit and CommitSig (reference: types/block.go:574-900).

A Commit is the +2/3 precommit aggregate persisted in every block's
LastCommit; each CommitSig records one validator's precommit (or absence).
Commit.vote_sign_bytes reconstructs the exact canonical bytes each validator
signed — the input rows of the TPU verification batch.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from cometbft_tpu import crypto
from cometbft_tpu.crypto import merkle
from cometbft_tpu.types.basic import BlockID, BlockIDFlag, SignedMsgType
from cometbft_tpu.types.vote import Vote
from cometbft_tpu.utils import cmttime
from cometbft_tpu.utils import protobuf as pb

MAX_COMMIT_OVERHEAD_BYTES = 94
MAX_COMMIT_SIG_BYTES = 109


@dataclass
class CommitSig:
    """types/block.go:586-600."""

    block_id_flag: BlockIDFlag
    validator_address: bytes = b""
    timestamp: cmttime.Timestamp = field(default_factory=cmttime.Timestamp.zero)
    signature: bytes = b""

    @classmethod
    def absent(cls) -> "CommitSig":
        return cls(block_id_flag=BlockIDFlag.ABSENT)

    def for_block(self) -> bool:
        return self.block_id_flag == BlockIDFlag.COMMIT

    def block_id(self, commit_block_id: BlockID) -> BlockID:
        """types/block.go:632-645."""
        if self.block_id_flag == BlockIDFlag.COMMIT:
            return commit_block_id
        return BlockID()

    def validate_basic(self) -> None:
        if self.block_id_flag not in (BlockIDFlag.ABSENT, BlockIDFlag.COMMIT, BlockIDFlag.NIL):
            raise ValueError(f"unknown BlockIDFlag: {self.block_id_flag}")
        if self.block_id_flag == BlockIDFlag.ABSENT:
            if self.validator_address:
                raise ValueError("validator address is present for absent CommitSig")
            if not self.timestamp.is_zero():
                raise ValueError("time is present for absent CommitSig")
            if self.signature:
                raise ValueError("signature is present for absent CommitSig")
        else:
            if len(self.validator_address) != crypto.ADDRESS_SIZE:
                raise ValueError("expected ValidatorAddress size to be 20 bytes")
            if not self.signature:
                raise ValueError("signature is missing")
            if len(self.signature) > crypto.MAX_SIGNATURE_SIZE:
                raise ValueError("signature is too big")

    def to_proto(self) -> bytes:
        w = pb.Writer()
        w.uvarint(1, int(self.block_id_flag))
        w.bytes(2, self.validator_address)
        w.message(3, pb.timestamp_bytes(self.timestamp.seconds, self.timestamp.nanos), always=True)
        w.bytes(4, self.signature)
        return w.output()

    @classmethod
    def from_proto(cls, data: bytes) -> "CommitSig":
        r = pb.Reader(data)
        cs = cls(block_id_flag=BlockIDFlag.ABSENT)
        while not r.at_end():
            f, w = r.read_tag()
            if f == 1:
                cs.block_id_flag = BlockIDFlag(r.read_uvarint())
            elif f == 2:
                cs.validator_address = r.read_bytes()
            elif f == 3:
                secs, nanos = r.read_timestamp()
                cs.timestamp = cmttime.Timestamp(secs, nanos)
            elif f == 4:
                cs.signature = r.read_bytes()
            else:
                r.skip(w)
        return cs


@dataclass
class Commit:
    """types/block.go:700-760."""

    height: int
    round_: int
    block_id: BlockID
    signatures: list[CommitSig]
    _hash: bytes | None = field(default=None, repr=False, compare=False)
    # chain_id -> rows; a dict (not a single-slot tuple) so alternating-
    # chain callers (light-client cross-chain paths, tests) don't silently
    # degrade to zero cache hits (ADVICE round-5). Bounded: a Commit is
    # only ever verified against a handful of chain ids.
    _sign_rows: dict | None = field(default=None, repr=False, compare=False)

    _MAX_SIGN_ROW_CHAINS = 4

    def size(self) -> int:
        return len(self.signatures)

    def get_vote(self, val_idx: int) -> Vote:
        """Reconstruct the precommit Vote for signature val_idx
        (types/block.go:857-869)."""
        cs = self.signatures[val_idx]
        return Vote(
            type_=SignedMsgType.PRECOMMIT,
            height=self.height,
            round_=self.round_,
            block_id=cs.block_id(self.block_id),
            timestamp=cs.timestamp,
            validator_address=cs.validator_address,
            validator_index=val_idx,
            signature=cs.signature,
        )

    def vote_sign_bytes(self, chain_id: str, val_idx: int) -> bytes:
        """types/block.go:880-883 — the batch-verification row builder."""
        return self.get_vote(val_idx).sign_bytes(chain_id)

    def vote_sign_bytes_all(self, chain_id: str):
        """All signatures' canonical sign-bytes in one pass, as a
        SharedPrefixRows container (libs/prefixrows.py) — indexing is
        byte-identical to vote_sign_bytes(chain_id, i) per index
        (asserted by tests). The CanonicalVote rows of one commit differ
        only in the timestamp field and the NIL-vote block_id omission,
        so the length varint + type/height/round/block_id head is built
        ONCE and kept FACTORED: COMMIT rows whose timestamp encodes to
        the commit's modal length store only their ~17-byte suffix
        (timestamp + chain tail); NIL votes and odd-length timestamps
        materialize as exception rows. The factored form flows through
        validation into kernel staging, where the whole run reassembles
        on the batch axis with one prefix broadcast instead of N row
        copies (the reduced-send protocol's host half) — per-row Writer
        construction was the dominant host cost of blocksync staging,
        and the prefix copies were most of what remained."""
        from collections import Counter

        from cometbft_tpu.libs.prefixrows import SharedPrefixRows
        from cometbft_tpu.types import canonical
        from cometbft_tpu.utils.protobuf import encode_uvarint

        if self._sign_rows is None:
            self._sign_rows = {}
        cached = self._sign_rows.get(chain_id)
        if cached is not None:
            return cached
        w = pb.Writer()
        w.uvarint(1, int(SignedMsgType.PRECOMMIT))
        w.sfixed64(2, self.height)
        w.sfixed64(3, self.round_)
        head_nil = w.output()  # NIL votes: block_id field omitted
        w.message(4, canonical.canonical_block_id_bytes(self.block_id))
        head_commit = w.output()
        tail = pb.Writer().string(6, chain_id).output()
        ts_tag = bytes([5 << 3 | 2])  # field 5, wire 2 (timestamp message)
        ts_all = [pb.timestamp_bytes(cs.timestamp.seconds,
                                     cs.timestamp.nanos)
                  for cs in self.signatures]
        # the shared prefix covers COMMIT rows at the commit's modal
        # timestamp-encoding length (the length varint in front of the
        # body pins the total row length, so an off-length timestamp
        # cannot share it)
        commit_lens = Counter(
            len(ts) for ts, cs in zip(ts_all, self.signatures)
            if cs.block_id_flag == BlockIDFlag.COMMIT)
        modal_ts_len = commit_lens.most_common(1)[0][0] if commit_lens else 0
        modal_body = (len(head_commit) + len(ts_tag)
                      + len(encode_uvarint(modal_ts_len)) + modal_ts_len
                      + len(tail))
        prefix = encode_uvarint(modal_body) + head_commit
        suffixes: list = []
        exceptions: dict[int, bytes] = {}
        for i, (ts, cs) in enumerate(zip(ts_all, self.signatures)):
            if (cs.block_id_flag == BlockIDFlag.COMMIT
                    and len(ts) == modal_ts_len):
                suffixes.append(ts_tag + encode_uvarint(len(ts)) + ts + tail)
                continue
            head = (head_commit if cs.block_id_flag == BlockIDFlag.COMMIT
                    else head_nil)
            body = head + ts_tag + encode_uvarint(len(ts)) + ts + tail
            suffixes.append(None)
            exceptions[i] = encode_uvarint(len(body)) + body
        rows = SharedPrefixRows(prefix, suffixes, exceptions)
        if len(self._sign_rows) >= self._MAX_SIGN_ROW_CHAINS:
            self._sign_rows.pop(next(iter(self._sign_rows)))
        self._sign_rows[chain_id] = rows
        return rows

    def hash(self) -> bytes:
        """Merkle root over CommitSig protos (types/block.go Commit.Hash)."""
        if self._hash is None:
            self._hash = merkle.hash_from_byte_slices(
                [cs.to_proto() for cs in self.signatures]
            )
        return self._hash

    def validate_basic(self) -> None:
        if self.height < 0:
            raise ValueError("negative Height")
        if self.round_ < 0:
            raise ValueError("negative Round")
        if self.height >= 1:
            if self.block_id.is_nil():
                raise ValueError("commit cannot be for nil block")
            if not self.signatures:
                raise ValueError("no signatures in commit")
            for cs in self.signatures:
                cs.validate_basic()

    def to_proto(self) -> bytes:
        w = pb.Writer()
        w.varint_i64(1, self.height)
        w.varint_i64(2, self.round_)
        w.message(3, self.block_id.to_proto(), always=True)
        for cs in self.signatures:
            w.message(4, cs.to_proto(), always=True)
        return w.output()

    @classmethod
    def from_proto(cls, data: bytes) -> "Commit":
        r = pb.Reader(data)
        c = cls(height=0, round_=0, block_id=BlockID(), signatures=[])
        while not r.at_end():
            f, w = r.read_tag()
            if f == 1:
                c.height = r.read_varint_i64()
            elif f == 2:
                c.round_ = r.read_varint_i64()
            elif f == 3:
                c.block_id = BlockID.from_proto(r.read_bytes())
            elif f == 4:
                c.signatures.append(CommitSig.from_proto(r.read_bytes()))
            else:
                r.skip(w)
        return c


@dataclass
class ExtendedCommitSig:
    """CommitSig + vote-extension data (types/block.go:741-800, ABCI 2.0)."""

    commit_sig: CommitSig
    extension: bytes = b""
    extension_signature: bytes = b""

    def validate_basic(self) -> None:
        self.commit_sig.validate_basic()
        if self.commit_sig.block_id_flag == BlockIDFlag.COMMIT:
            return
        if self.extension:
            raise ValueError("vote extension is present for non-commit CommitSig")
        if self.extension_signature:
            raise ValueError("vote extension signature is present for non-commit CommitSig")


@dataclass
class ExtendedCommit:
    """types/block.go:708-856: a commit carrying vote extensions, stored for
    the latest height to rebuild LastCommit precommits (for PrepareProposal)."""

    height: int
    round_: int
    block_id: BlockID
    extended_signatures: list[ExtendedCommitSig]

    def to_commit(self) -> Commit:
        return Commit(
            height=self.height,
            round_=self.round_,
            block_id=self.block_id,
            signatures=[e.commit_sig for e in self.extended_signatures],
        )

    def size(self) -> int:
        return len(self.extended_signatures)

    def get_extended_vote(self, val_idx: int) -> Vote:
        e = self.extended_signatures[val_idx]
        v = self.to_commit().get_vote(val_idx)
        v.extension = e.extension
        v.extension_signature = e.extension_signature
        return v

    def ensure_extensions(self, required: bool) -> None:
        """types/block.go:765-785."""
        for e in self.extended_signatures:
            cs = e.commit_sig
            if required and cs.block_id_flag == BlockIDFlag.COMMIT and not e.extension_signature:
                raise ValueError("vote extension signature is missing")
            if cs.block_id_flag != BlockIDFlag.COMMIT and (e.extension or e.extension_signature):
                raise ValueError("non-commit vote carries extension data")
