"""Vote — the unit of consensus signaling (reference: types/vote.go).

Sign-bytes are the canonical encoding (canonical.py); wire encoding is the
tendermint.types.Vote proto (types.proto:83-103) used by the WAL, p2p
envelopes, and the privval protocol.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from cometbft_tpu import crypto
from cometbft_tpu.types import canonical
from cometbft_tpu.types.basic import BlockID, SignedMsgType
from cometbft_tpu.utils import cmttime
from cometbft_tpu.utils import protobuf as pb

MAX_VOTE_BYTES = 223  # types/vote.go MaxVoteBytes (without extensions)


@dataclass
class Vote:
    type_: SignedMsgType
    height: int
    round_: int
    block_id: BlockID
    timestamp: cmttime.Timestamp
    validator_address: bytes
    validator_index: int
    signature: bytes = b""
    extension: bytes = b""
    extension_signature: bytes = b""

    def is_nil(self) -> bool:
        """A vote for 'nil' — explicitly against the proposal."""
        return self.block_id.is_nil()

    def sign_bytes(self, chain_id: str) -> bytes:
        return canonical.vote_sign_bytes(
            chain_id, self.type_, self.height, self.round_, self.block_id, self.timestamp
        )

    def extension_sign_bytes(self, chain_id: str) -> bytes:
        return canonical.vote_extension_sign_bytes(
            chain_id, self.height, self.round_, self.extension
        )

    def verify(self, chain_id: str, pub_key: crypto.PubKey) -> bool:
        """Serial-path verification (reference types/vote.go:224). The batch
        path goes through VoteSet/validation instead."""
        if pub_key.address() != self.validator_address:
            return False
        return pub_key.verify_signature(self.sign_bytes(chain_id), self.signature)

    def verify_extension(self, chain_id: str, pub_key: crypto.PubKey) -> bool:
        """Only the extension signature (types/vote.go:247 VerifyExtension)
        — the gate before the app sees the payload; the vote's own
        signature verifies separately (serial add or device-batch flush)."""
        if self.type_ != SignedMsgType.PRECOMMIT or self.block_id.is_nil():
            return True
        if not self.extension_signature:
            return False
        return pub_key.verify_signature(
            self.extension_sign_bytes(chain_id), self.extension_signature
        )

    def verify_vote_and_extension(self, chain_id: str, pub_key: crypto.PubKey) -> bool:
        if not self.verify(chain_id, pub_key):
            return False
        if self.type_ == SignedMsgType.PRECOMMIT and not self.block_id.is_nil():
            if not self.extension_signature:
                return False
            return pub_key.verify_signature(
                self.extension_sign_bytes(chain_id), self.extension_signature
            )
        return True

    def validate_basic(self) -> None:
        """types/vote.go ValidateBasic."""
        if self.type_ not in (SignedMsgType.PREVOTE, SignedMsgType.PRECOMMIT):
            raise ValueError("invalid Type")
        if self.height <= 0:
            raise ValueError("non-positive Height")
        if self.round_ < 0:
            raise ValueError("negative Round")
        self.block_id.validate_basic()
        if not self.block_id.is_nil() and not self.block_id.is_complete():
            raise ValueError(f"blockID must be either empty or complete, got: {self.block_id}")
        if len(self.validator_address) != crypto.ADDRESS_SIZE:
            raise ValueError("expected ValidatorAddress size to be 20 bytes")
        if self.validator_index < 0:
            raise ValueError("negative ValidatorIndex")
        if not self.signature:
            raise ValueError("signature is missing")
        if self.type_ != SignedMsgType.PRECOMMIT or self.is_nil():
            if self.extension:
                raise ValueError("unexpected vote extension")
            if self.extension_signature:
                raise ValueError("unexpected extension signature")

    # ------------------------------------------------------------- proto

    def to_proto(self) -> bytes:
        w = pb.Writer()
        w.uvarint(1, int(self.type_))
        w.varint_i64(2, self.height)
        w.varint_i64(3, self.round_)
        w.message(4, self.block_id.to_proto(), always=True)
        w.message(5, pb.timestamp_bytes(self.timestamp.seconds, self.timestamp.nanos), always=True)
        w.bytes(6, self.validator_address)
        w.varint_i64(7, self.validator_index)
        w.bytes(8, self.signature)
        w.bytes(9, self.extension)
        w.bytes(10, self.extension_signature)
        return w.output()

    @classmethod
    def from_proto(cls, data: bytes) -> "Vote":
        r = pb.Reader(data)
        v = cls(
            type_=SignedMsgType.UNKNOWN,
            height=0,
            round_=0,
            block_id=BlockID(),
            timestamp=cmttime.Timestamp.zero(),
            validator_address=b"",
            validator_index=0,
        )
        while not r.at_end():
            f, w = r.read_tag()
            if f == 1:
                v.type_ = SignedMsgType(r.read_uvarint())
            elif f == 2:
                v.height = r.read_varint_i64()
            elif f == 3:
                v.round_ = r.read_varint_i64()
            elif f == 4:
                v.block_id = BlockID.from_proto(r.read_bytes())
            elif f == 5:
                secs, nanos = r.read_timestamp()
                v.timestamp = cmttime.Timestamp(secs, nanos)
            elif f == 6:
                v.validator_address = r.read_bytes()
            elif f == 7:
                v.validator_index = r.read_varint_i64()
            elif f == 8:
                v.signature = r.read_bytes()
            elif f == 9:
                v.extension = r.read_bytes()
            elif f == 10:
                v.extension_signature = r.read_bytes()
            else:
                r.skip(w)
        return v

    def __str__(self) -> str:
        kind = {SignedMsgType.PREVOTE: "Prevote", SignedMsgType.PRECOMMIT: "Precommit"}.get(
            self.type_, "?"
        )
        return (
            f"Vote{{{self.validator_index}:{self.validator_address.hex()[:12]} "
            f"{self.height}/{self.round_} {kind} {self.block_id}}}"
        )
