"""Byzantine evidence types (reference: types/evidence.go).

DuplicateVoteEvidence — equivocation: two signed votes for the same
height/round/type but different blocks. LightClientAttackEvidence — a
conflicting light block trace. Verification lives in evidence/verify.py
(pool-side); here are the types, hashing, and ABCI conversion.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from cometbft_tpu.crypto import tmhash
from cometbft_tpu.types.validator import Validator, ValidatorSet
from cometbft_tpu.types.vote import Vote
from cometbft_tpu.utils import cmttime
from cometbft_tpu.utils import protobuf as pb


class Evidence:
    """types/evidence.go Evidence interface."""

    def abci(self) -> list[dict]:
        raise NotImplementedError

    def bytes_(self) -> bytes:
        raise NotImplementedError

    def hash(self) -> bytes:
        return tmhash.sum_(self.bytes_())

    def height(self) -> int:
        raise NotImplementedError

    def time(self) -> cmttime.Timestamp:
        raise NotImplementedError

    def validate_basic(self) -> None:
        raise NotImplementedError

    def string(self) -> str:
        raise NotImplementedError


@dataclass
class DuplicateVoteEvidence(Evidence):
    """types/evidence.go:53-71."""

    vote_a: Vote
    vote_b: Vote
    total_voting_power: int = 0
    validator_power: int = 0
    timestamp: cmttime.Timestamp = field(default_factory=cmttime.Timestamp.zero)

    @classmethod
    def new(
        cls,
        vote1: Vote,
        vote2: Vote,
        block_time: cmttime.Timestamp,
        val_set: ValidatorSet,
    ) -> "DuplicateVoteEvidence":
        """types/evidence.go NewDuplicateVoteEvidence: orders votes by
        BlockID key, fills powers from the valset."""
        if vote1 is None or vote2 is None or val_set is None:
            raise ValueError("missing vote or validator set")
        _, val = val_set.get_by_address(vote1.validator_address)
        if val is None:
            raise ValueError("validator is not in validator set")
        if vote1.block_id.key() < vote2.block_id.key():
            vote_a, vote_b = vote1, vote2
        else:
            vote_a, vote_b = vote2, vote1
        return cls(
            vote_a=vote_a,
            vote_b=vote_b,
            total_voting_power=val_set.total_voting_power(),
            validator_power=val.voting_power,
            timestamp=block_time,
        )

    def abci(self) -> list[dict]:
        return [
            {
                "type": "DUPLICATE_VOTE",
                "validator_address": self.vote_a.validator_address,
                "validator_power": self.validator_power,
                "height": self.vote_a.height,
                "time": self.timestamp,
                "total_voting_power": self.total_voting_power,
            }
        ]

    def bytes_(self) -> bytes:
        return self.to_proto()

    def height(self) -> int:
        return self.vote_a.height

    def time(self) -> cmttime.Timestamp:
        return self.timestamp

    def validate_basic(self) -> None:
        if self.vote_a is None or self.vote_b is None:
            raise ValueError("empty duplicate vote evidence")
        self.vote_a.validate_basic()
        self.vote_b.validate_basic()
        if self.vote_a.block_id.key() >= self.vote_b.block_id.key():
            raise ValueError("duplicate votes in invalid order")

    def string(self) -> str:
        return f"DuplicateVoteEvidence{{VoteA: {self.vote_a}, VoteB: {self.vote_b}}}"

    def to_proto(self) -> bytes:
        w = pb.Writer()
        w.message(1, self.vote_a.to_proto(), always=True)
        w.message(2, self.vote_b.to_proto(), always=True)
        w.varint_i64(3, self.total_voting_power)
        w.varint_i64(4, self.validator_power)
        w.message(
            5, pb.timestamp_bytes(self.timestamp.seconds, self.timestamp.nanos), always=True
        )
        return w.output()

    @classmethod
    def from_proto(cls, data: bytes) -> "DuplicateVoteEvidence":
        r = pb.Reader(data)
        ev = cls(vote_a=None, vote_b=None)  # type: ignore[arg-type]
        while not r.at_end():
            f, w = r.read_tag()
            if f == 1:
                ev.vote_a = Vote.from_proto(r.read_bytes())
            elif f == 2:
                ev.vote_b = Vote.from_proto(r.read_bytes())
            elif f == 3:
                ev.total_voting_power = r.read_varint_i64()
            elif f == 4:
                ev.validator_power = r.read_varint_i64()
            elif f == 5:
                secs, nanos = r.read_timestamp()
                ev.timestamp = cmttime.Timestamp(secs, nanos)
            else:
                r.skip(w)
        return ev


@dataclass
class LightClientAttackEvidence(Evidence):
    """types/evidence.go:203-260. Carries the conflicting light block and the
    common height; byzantine validators filled in by the evidence pool."""

    conflicting_block: "object"  # light.LightBlock (avoid circular import)
    common_height: int
    byzantine_validators: list[Validator] = field(default_factory=list)
    total_voting_power: int = 0
    timestamp: cmttime.Timestamp = field(default_factory=cmttime.Timestamp.zero)

    def abci(self) -> list[dict]:
        return [
            {
                "type": "LIGHT_CLIENT_ATTACK",
                "validator_address": v.address,
                "validator_power": v.voting_power,
                "height": self.height(),
                "time": self.timestamp,
                "total_voting_power": self.total_voting_power,
            }
            for v in self.byzantine_validators
        ]

    def bytes_(self) -> bytes:
        return self.to_proto()

    def to_proto(self) -> bytes:
        """tendermint.types.LightClientAttackEvidence: conflicting_block=1,
        common_height=2, byzantine_validators=3, total_voting_power=4,
        timestamp=5."""
        w = pb.Writer()
        w.message(1, self.conflicting_block.to_proto(), always=True)
        w.varint_i64(2, self.common_height)
        for v in self.byzantine_validators:
            w.message(3, v.to_proto(), always=True)
        w.varint_i64(4, self.total_voting_power)
        w.message(
            5, pb.timestamp_bytes(self.timestamp.seconds, self.timestamp.nanos), always=True
        )
        return w.output()

    @classmethod
    def from_proto(cls, data: bytes) -> "LightClientAttackEvidence":
        from cometbft_tpu.types.light import LightBlock

        r = pb.Reader(data)
        ev = cls(conflicting_block=None, common_height=0)
        while not r.at_end():
            f, w = r.read_tag()
            if f == 1:
                ev.conflicting_block = LightBlock.from_proto(r.read_bytes())
            elif f == 2:
                ev.common_height = r.read_varint_i64()
            elif f == 3:
                ev.byzantine_validators.append(Validator.from_proto(r.read_bytes()))
            elif f == 4:
                ev.total_voting_power = r.read_varint_i64()
            elif f == 5:
                secs, nanos = r.read_timestamp()
                ev.timestamp = cmttime.Timestamp(secs, nanos)
            else:
                r.skip(w)
        return ev

    def hash(self) -> bytes:
        """types/evidence.go:322-329: header hash + common height — stable
        across byzantine-validator permutations (dedup key)."""
        w = pb.Writer()
        w.bytes(1, self.conflicting_block.hash() or b"")
        w.varint_i64(2, self.common_height)
        return tmhash.sum_(w.output())

    def conflicting_header_is_invalid(self, trusted_header) -> bool:
        """types/evidence.go:303-312: lunatic iff any state-derived header
        field differs from the trusted header at the same height."""
        ch = self.conflicting_block.header
        return (
            trusted_header.validators_hash != ch.validators_hash
            or trusted_header.next_validators_hash != ch.next_validators_hash
            or trusted_header.consensus_hash != ch.consensus_hash
            or trusted_header.app_hash != ch.app_hash
            or trusted_header.last_results_hash != ch.last_results_hash
        )

    def get_byzantine_validators(self, common_vals: ValidatorSet,
                                 trusted) -> list[Validator]:
        """types/evidence.go:250-300: classify the attack and extract the
        culprits. Lunatic -> signers of the conflicting commit who are in
        the common valset; equivocation (same round) -> validators who
        signed both commits; amnesia (different rounds) -> unknown."""
        from cometbft_tpu.types.basic import BlockIDFlag

        out: list[Validator] = []
        conflicting = self.conflicting_block
        if self.conflicting_header_is_invalid(trusted.header):
            for cs in conflicting.commit.signatures:
                if cs.block_id_flag != BlockIDFlag.COMMIT:
                    continue
                _, val = common_vals.get_by_address(cs.validator_address)
                if val is None:
                    continue
                out.append(val)
        elif trusted.commit.round_ == conflicting.commit.round_:
            for i, sig_a in enumerate(conflicting.commit.signatures):
                if sig_a.block_id_flag != BlockIDFlag.COMMIT:
                    continue
                if i >= len(trusted.commit.signatures):
                    continue
                sig_b = trusted.commit.signatures[i]
                if sig_b.block_id_flag != BlockIDFlag.COMMIT:
                    continue
                _, val = conflicting.validator_set.get_by_address(sig_a.validator_address)
                if val is not None:
                    out.append(val)
        out.sort(key=lambda v: (-v.voting_power, v.address))
        return out

    def height(self) -> int:
        return self.common_height

    def time(self) -> cmttime.Timestamp:
        return self.timestamp

    def validate_basic(self) -> None:
        if self.conflicting_block is None:
            raise ValueError("conflicting block is nil")
        if self.common_height <= 0:
            raise ValueError("negative or zero common height")

    def string(self) -> str:
        return f"LightClientAttackEvidence{{CommonHeight: {self.common_height}}}"


def evidence_list_to_proto(evs: list[Evidence]) -> bytes:
    """tendermint.types.EvidenceList: repeated oneof-wrapped evidence."""
    w = pb.Writer()
    for ev in evs:
        inner = pb.Writer()
        if isinstance(ev, DuplicateVoteEvidence):
            inner.message(1, ev.to_proto(), always=True)
        elif isinstance(ev, LightClientAttackEvidence):
            inner.message(2, ev.to_proto(), always=True)
        else:
            raise ValueError(f"unsupported evidence type for wire: {type(ev)}")
        w.message(1, inner.output(), always=True)
    return w.output()


def evidence_list_from_proto(data: bytes) -> list[Evidence]:
    out: list[Evidence] = []
    r = pb.Reader(data)
    while not r.at_end():
        f, w = r.read_tag()
        if f == 1:
            er = r.read_message()
            while not er.at_end():
                ef, ew = er.read_tag()
                if ef == 1:
                    out.append(DuplicateVoteEvidence.from_proto(er.read_bytes()))
                elif ef == 2:
                    out.append(LightClientAttackEvidence.from_proto(er.read_bytes()))
                else:
                    er.skip(ew)
        else:
            r.skip(w)
    return out
