"""EventBus: typed event publishing over the query-addressable pubsub.

Reference: types/event_bus.go:33 + types/events.go. Every event carries a
composite-keyed attribute map; `tm.event` identifies the type, ABCI events
from FinalizeBlock are flattened in as `<type>.<attr>` keys, and txs also
get the reserved `tx.hash` / `tx.height` keys (types/event_bus.go:160-200).
Subscribers (RPC websocket clients, the indexer service) filter with pubsub
queries like "tm.event = 'Tx' AND tx.hash = '...'".
"""

from __future__ import annotations

from dataclasses import dataclass

from cometbft_tpu.crypto import tmhash
from cometbft_tpu.libs import pubsub

# reserved event types (types/events.go)
EVENT_NEW_BLOCK = "NewBlock"
EVENT_NEW_BLOCK_HEADER = "NewBlockHeader"
EVENT_NEW_BLOCK_EVENTS = "NewBlockEvents"
EVENT_TX = "Tx"
EVENT_VALIDATOR_SET_UPDATES = "ValidatorSetUpdates"
EVENT_NEW_ROUND = "NewRound"
EVENT_NEW_ROUND_STEP = "NewRoundStep"
EVENT_COMPLETE_PROPOSAL = "CompleteProposal"
EVENT_VOTE = "Vote"
EVENT_LOCK = "Lock"
EVENT_UNLOCK = "Unlock"
EVENT_POLKA = "Polka"
EVENT_VALID_BLOCK = "ValidBlock"

EVENT_TYPE_KEY = "tm.event"
TX_HASH_KEY = "tx.hash"
TX_HEIGHT_KEY = "tx.height"


def query_for_event(event_type: str) -> str:
    return f"{EVENT_TYPE_KEY} = '{event_type}'"


QUERY_NEW_BLOCK = query_for_event(EVENT_NEW_BLOCK)
QUERY_TX = query_for_event(EVENT_TX)


# ------------------------------------------------------- event data types


@dataclass
class EventDataNewBlock:
    block: object
    block_id: object
    result_finalize_block: object


@dataclass
class EventDataTx:
    height: int
    tx: bytes
    index: int
    result: object  # ExecTxResult


@dataclass
class EventDataValidatorSetUpdates:
    validator_updates: list


@dataclass
class EventDataRoundState:
    height: int
    round_: int
    step: str


def _flatten_abci_events(events, out: dict[str, list[str]]) -> None:
    """types/event_bus.go:60-80: '<type>.<key>' -> [values] for indexed
    attributes."""
    for ev in events or []:
        if not ev.type_:
            continue
        for attr in ev.attributes:
            if not attr.key or not attr.index:
                continue
            out.setdefault(f"{ev.type_}.{attr.key}", []).append(attr.value)


class EventBus:
    """types/event_bus.go:33 — the async event plane (RPC + indexers)."""

    def __init__(self, capacity: int = 1024):
        self.server = pubsub.Server(capacity_per_subscription=capacity)

    # ------------------------------------------------------- subscriptions

    def subscribe(self, client_id: str, query: str,
                  capacity: int | None = None) -> pubsub.Subscription:
        return self.server.subscribe(client_id, query, capacity)

    def unsubscribe(self, client_id: str, query: str) -> None:
        self.server.unsubscribe(client_id, query)

    def unsubscribe_all(self, client_id: str) -> None:
        self.server.unsubscribe_all(client_id)

    # --------------------------------------------------------- publishing

    async def publish(self, event_type: str, data) -> None:
        self.server.publish(data, {EVENT_TYPE_KEY: [event_type]})

    async def publish_event_new_block(self, block, block_id, resp) -> None:
        events = {EVENT_TYPE_KEY: [EVENT_NEW_BLOCK]}
        _flatten_abci_events(getattr(resp, "events", None), events)
        self.server.publish(EventDataNewBlock(block, block_id, resp), events)

    async def publish_event_tx(self, height: int, tx: bytes, index: int,
                               result) -> None:
        """types/event_bus.go:160-200 PublishEventTx: reserved keys always
        indexed."""
        events = {
            EVENT_TYPE_KEY: [EVENT_TX],
            TX_HASH_KEY: [tmhash.sum_(tx).hex().upper()],
            TX_HEIGHT_KEY: [str(height)],
        }
        _flatten_abci_events(getattr(result, "events", None), events)
        self.server.publish(EventDataTx(height, tx, index, result), events)

    async def publish_event_validator_set_updates(self, updates) -> None:
        await self.publish(
            EVENT_VALIDATOR_SET_UPDATES, EventDataValidatorSetUpdates(updates))

    async def publish_round_event(self, event_type: str, height: int,
                                  round_: int, step: str) -> None:
        await self.publish(event_type, EventDataRoundState(height, round_, step))
