"""SignedHeader and LightBlock — the light-client domain types.

Reference: types/light.go (LightBlock, SignedHeader). A SignedHeader is a
header plus the commit that signed it; a LightBlock adds the validator set
whose hash the header carries. validate_basic mirrors types/light.go:13-60
and types/block.go SignedHeader.ValidateBasic.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

from cometbft_tpu.types.block import Header
from cometbft_tpu.types.commit import Commit
from cometbft_tpu.types.validator import ValidatorSet
from cometbft_tpu.utils import protobuf as pb


@dataclass
class SignedHeader:
    """types/block.go SignedHeader: header + the commit over it."""

    header: Header
    commit: Commit

    @property
    def height(self) -> int:
        return self.header.height

    @property
    def time(self):
        return self.header.time

    @property
    def chain_id(self) -> str:
        return self.header.chain_id

    def hash(self) -> bytes | None:
        return self.header.hash()

    def validate_basic(self, chain_id: str) -> None:
        """types/block.go SignedHeader.ValidateBasic: header and commit are
        self-consistent and commit actually points at this header."""
        if self.header is None:
            raise ValueError("missing header")
        if self.commit is None:
            raise ValueError("missing commit")
        self.header.validate_basic()
        self.commit.validate_basic()
        if self.header.chain_id != chain_id:
            raise ValueError(
                f"header belongs to another chain {self.header.chain_id!r}, not {chain_id!r}"
            )
        if self.commit.height != self.header.height:
            raise ValueError(
                f"header and commit height mismatch: {self.header.height} vs {self.commit.height}"
            )
        if self.commit.block_id.hash != self.header.hash():
            raise ValueError("commit signs a different header")

    def to_proto(self) -> bytes:
        w = pb.Writer()
        w.message(1, self.header.to_proto(), always=True)
        w.message(2, self.commit.to_proto(), always=True)
        return w.output()

    @classmethod
    def from_proto(cls, data: bytes) -> "SignedHeader":
        r = pb.Reader(data)
        header, commit = None, None
        while not r.at_end():
            f, w = r.read_tag()
            if f == 1:
                header = Header.from_proto(r.read_bytes())
            elif f == 2:
                commit = Commit.from_proto(r.read_bytes())
            else:
                r.skip(w)
        if header is None or commit is None:
            raise ValueError("incomplete SignedHeader proto")
        return cls(header=header, commit=commit)


@dataclass
class LightBlock:
    """types/light.go:100-150: SignedHeader + the validator set for that
    height. The light client's unit of transfer and trust."""

    signed_header: SignedHeader
    validator_set: ValidatorSet

    @property
    def height(self) -> int:
        return self.signed_header.height

    @property
    def time(self):
        return self.signed_header.time

    def hash(self) -> bytes | None:
        return self.signed_header.hash()

    @property
    def header(self) -> Header:
        return self.signed_header.header

    @property
    def commit(self) -> Commit:
        return self.signed_header.commit

    def validate_basic(self, chain_id: str) -> None:
        """types/light.go:30-60: inner checks plus the valset-hash link."""
        if self.signed_header is None:
            raise ValueError("missing signed header")
        if self.validator_set is None or self.validator_set.is_nil_or_empty():
            raise ValueError("missing validator set")
        self.signed_header.validate_basic(chain_id)
        self.validator_set.validate_basic()
        if self.signed_header.header.validators_hash != self.validator_set.hash():
            raise ValueError(
                "light block's validator set hash does not match its header's"
            )

    def to_proto(self) -> bytes:
        w = pb.Writer()
        w.message(1, self.signed_header.to_proto(), always=True)
        w.message(2, self.validator_set.to_proto(), always=True)
        return w.output()

    @classmethod
    def from_proto(cls, data: bytes) -> "LightBlock":
        r = pb.Reader(data)
        sh: Optional[SignedHeader] = None
        vs: Optional[ValidatorSet] = None
        while not r.at_end():
            f, w = r.read_tag()
            if f == 1:
                sh = SignedHeader.from_proto(r.read_bytes())
            elif f == 2:
                vs = ValidatorSet.from_proto(r.read_bytes())
            else:
                r.skip(w)
        if sh is None or vs is None:
            raise ValueError("incomplete LightBlock proto")
        return cls(signed_header=sh, validator_set=vs)
