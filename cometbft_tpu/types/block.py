"""Block, Header, Data — and their consensus-critical hashes.

Reference: types/block.go. Header.hash() is the merkle root over the 14
field encodings (block.go:439-474) using gogoproto wrapper encodings
(types/encoding_helper.go cdcEncode); Data.hash() is the tx merkle root;
Block.hash() == Header.hash().
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass, field

from cometbft_tpu.crypto import merkle, tmhash
from cometbft_tpu.types.basic import BlockID, PartSetHeader
from cometbft_tpu.types.commit import Commit
from cometbft_tpu.utils import cmttime
from cometbft_tpu.utils import protobuf as pb

# Block protocol version (reference: version/version.go BlockProtocol = 11)
BLOCK_PROTOCOL = 11
MAX_HEADER_BYTES = 626


def cdc_encode_string(s: str) -> bytes:
    """gogotypes.StringValue marshal (encoding_helper.go:14-22);
    empty -> nil leaf."""
    if not s:
        return b""
    return pb.Writer().string(1, s).output()


def cdc_encode_int64(v: int) -> bytes:
    if not v:
        return b""
    return pb.Writer().varint_i64(1, v).output()


def cdc_encode_bytes(v: bytes) -> bytes:
    if not v:
        return b""
    return pb.Writer().bytes(1, v).output()


@dataclass
class Consensus:
    """version.Consensus proto (proto/tendermint/version/types.proto:19-24)."""

    block: int = BLOCK_PROTOCOL
    app: int = 0

    def to_proto(self) -> bytes:
        return pb.Writer().uvarint(1, self.block).uvarint(2, self.app).output()

    @classmethod
    def from_proto(cls, data: bytes) -> "Consensus":
        r = pb.Reader(data)
        c = cls(block=0, app=0)
        while not r.at_end():
            f, w = r.read_tag()
            if f == 1:
                c.block = r.read_uvarint()
            elif f == 2:
                c.app = r.read_uvarint()
            else:
                r.skip(w)
        return c


@dataclass
class Header:
    """types/block.go:337-360."""

    version: Consensus = field(default_factory=Consensus)
    chain_id: str = ""
    height: int = 0
    time: cmttime.Timestamp = field(default_factory=cmttime.Timestamp.zero)
    last_block_id: BlockID = field(default_factory=BlockID)
    last_commit_hash: bytes = b""
    data_hash: bytes = b""
    validators_hash: bytes = b""
    next_validators_hash: bytes = b""
    consensus_hash: bytes = b""
    app_hash: bytes = b""
    last_results_hash: bytes = b""
    evidence_hash: bytes = b""
    proposer_address: bytes = b""

    def hash(self) -> bytes | None:
        """block.go:439-474. None when the header is incomplete (pre-populate)."""
        if not self.validators_hash:
            return None
        return merkle.hash_from_byte_slices(
            [
                self.version.to_proto(),
                cdc_encode_string(self.chain_id),
                cdc_encode_int64(self.height),
                pb.timestamp_bytes(self.time.seconds, self.time.nanos),
                self.last_block_id.to_proto(),
                cdc_encode_bytes(self.last_commit_hash),
                cdc_encode_bytes(self.data_hash),
                cdc_encode_bytes(self.validators_hash),
                cdc_encode_bytes(self.next_validators_hash),
                cdc_encode_bytes(self.consensus_hash),
                cdc_encode_bytes(self.app_hash),
                cdc_encode_bytes(self.last_results_hash),
                cdc_encode_bytes(self.evidence_hash),
                cdc_encode_bytes(self.proposer_address),
            ]
        )

    def validate_basic(self) -> None:
        """block.go Header.ValidateBasic."""
        if len(self.chain_id) > 50:
            raise ValueError("chainID is too long")
        if self.height < 0:
            raise ValueError("negative Header.Height")
        if self.height == 0:
            raise ValueError("zero Header.Height")
        self.last_block_id.validate_basic()
        for name, h in (
            ("LastCommitHash", self.last_commit_hash),
            ("DataHash", self.data_hash),
            ("EvidenceHash", self.evidence_hash),
            ("ValidatorsHash", self.validators_hash),
            ("NextValidatorsHash", self.next_validators_hash),
            ("ConsensusHash", self.consensus_hash),
            ("LastResultsHash", self.last_results_hash),
        ):
            if h and len(h) != tmhash.SIZE:
                raise ValueError(f"wrong {name} size {len(h)}")
        if len(self.proposer_address) != 20:
            raise ValueError("invalid ProposerAddress length")

    def to_proto(self) -> bytes:
        w = pb.Writer()
        w.message(1, self.version.to_proto(), always=True)
        w.string(2, self.chain_id)
        w.varint_i64(3, self.height)
        w.message(4, pb.timestamp_bytes(self.time.seconds, self.time.nanos), always=True)
        w.message(5, self.last_block_id.to_proto(), always=True)
        w.bytes(6, self.last_commit_hash)
        w.bytes(7, self.data_hash)
        w.bytes(8, self.validators_hash)
        w.bytes(9, self.next_validators_hash)
        w.bytes(10, self.consensus_hash)
        w.bytes(11, self.app_hash)
        w.bytes(12, self.last_results_hash)
        w.bytes(13, self.evidence_hash)
        w.bytes(14, self.proposer_address)
        return w.output()

    @classmethod
    def from_proto(cls, data: bytes) -> "Header":
        r = pb.Reader(data)
        h = cls()
        while not r.at_end():
            f, w = r.read_tag()
            if f == 1:
                h.version = Consensus.from_proto(r.read_bytes())
            elif f == 2:
                h.chain_id = r.read_string()
            elif f == 3:
                h.height = r.read_varint_i64()
            elif f == 4:
                secs, nanos = r.read_timestamp()
                h.time = cmttime.Timestamp(secs, nanos)
            elif f == 5:
                h.last_block_id = BlockID.from_proto(r.read_bytes())
            elif f == 6:
                h.last_commit_hash = r.read_bytes()
            elif f == 7:
                h.data_hash = r.read_bytes()
            elif f == 8:
                h.validators_hash = r.read_bytes()
            elif f == 9:
                h.next_validators_hash = r.read_bytes()
            elif f == 10:
                h.consensus_hash = r.read_bytes()
            elif f == 11:
                h.app_hash = r.read_bytes()
            elif f == 12:
                h.last_results_hash = r.read_bytes()
            elif f == 13:
                h.evidence_hash = r.read_bytes()
            elif f == 14:
                h.proposer_address = r.read_bytes()
            else:
                r.skip(w)
        return h


def tx_hash(tx: bytes) -> bytes:
    """types/tx.go Tx.Hash — SHA-256 of the raw tx bytes."""
    return hashlib.sha256(tx).digest()


@dataclass
class Data:
    """Block transactions (types/block.go Data)."""

    txs: list[bytes] = field(default_factory=list)
    _hash: bytes | None = field(default=None, repr=False, compare=False)

    def hash(self) -> bytes:
        """Merkle root over raw txs (types/tx.go Txs.Hash — leaves are the
        raw transactions, NOT their hashes)."""
        if self._hash is None:
            self._hash = merkle.hash_from_byte_slices(list(self.txs))
        return self._hash


@dataclass
class EvidenceData:
    """types/evidence.go EvidenceData — list of committed evidence."""

    evidence: list = field(default_factory=list)
    _hash: bytes | None = field(default=None, repr=False, compare=False)

    def hash(self) -> bytes:
        if self._hash is None:
            self._hash = merkle.hash_from_byte_slices(
                [ev.bytes_() for ev in self.evidence]
            )
        return self._hash


@dataclass
class Block:
    """types/block.go:27-45."""

    header: Header
    data: Data
    evidence: EvidenceData
    last_commit: Commit | None

    def hash(self) -> bytes | None:
        self.fill_header()
        return self.header.hash()

    def fill_header(self) -> None:
        """block.go fillHeader: populate derived hashes if unset."""
        if not self.header.last_commit_hash and self.last_commit is not None:
            self.header.last_commit_hash = self.last_commit.hash()
        if not self.header.data_hash:
            self.header.data_hash = self.data.hash()
        if not self.header.evidence_hash:
            self.header.evidence_hash = self.evidence.hash()

    def validate_basic(self) -> None:
        """block.go ValidateBasic. LastCommit is required unconditionally —
        first-height blocks carry an EMPTY (zero-signature) commit, never a
        nil one (the reference likewise rejects nil at any height, and a
        height-1 special case would also be wrong for chains whose
        initial_height > 1)."""
        self.header.validate_basic()
        if self.last_commit is None:
            raise ValueError("nil LastCommit")
        self.last_commit.validate_basic()
        if self.header.last_commit_hash != self.last_commit.hash():
            raise ValueError("wrong LastCommitHash")
        if self.header.data_hash != self.data.hash():
            raise ValueError("wrong DataHash")
        if self.header.evidence_hash != self.evidence.hash():
            raise ValueError("wrong EvidenceHash")

    def make_part_set(self, part_size: int):
        from cometbft_tpu.types.part_set import PartSet

        return PartSet.from_data(self.to_proto(), part_size)

    def to_proto(self) -> bytes:
        from cometbft_tpu.types.evidence import evidence_list_to_proto

        w = pb.Writer()
        w.message(1, self.header.to_proto(), always=True)
        data_w = pb.Writer()
        for tx in self.data.txs:
            data_w.bytes(1, tx, always=True)
        w.message(2, data_w.output(), always=True)
        w.message(3, evidence_list_to_proto(self.evidence.evidence), always=True)
        if self.last_commit is not None:
            w.message(4, self.last_commit.to_proto())
        return w.output()

    @classmethod
    def from_proto(cls, data: bytes) -> "Block":
        from cometbft_tpu.types.evidence import evidence_list_from_proto

        r = pb.Reader(data)
        header = Header()
        txs: list[bytes] = []
        evidence: list = []
        last_commit = None
        while not r.at_end():
            f, w = r.read_tag()
            if f == 1:
                header = Header.from_proto(r.read_bytes())
            elif f == 2:
                dr = r.read_message()
                while not dr.at_end():
                    df, dw = dr.read_tag()
                    if df == 1:
                        txs.append(dr.read_bytes())
                    else:
                        dr.skip(dw)
            elif f == 3:
                evidence = evidence_list_from_proto(r.read_bytes())
            elif f == 4:
                last_commit = Commit.from_proto(r.read_bytes())
            else:
                r.skip(w)
        return cls(
            header=header,
            data=Data(txs=txs),
            evidence=EvidenceData(evidence=evidence),
            last_commit=last_commit,
        )
