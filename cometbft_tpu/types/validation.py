"""Commit verification over the batch-first crypto boundary.

Reference: types/validation.go. All three entry points funnel signature rows
into one BatchVerifier (TPU kernel or CPU loop, crypto/batch dispatch) —
on failure the per-lane mask pinpoints the first bad signature without the
reference's serial re-verify pass (types/validation.go:266).

Semantics preserved exactly:
  verify_commit            — counts only COMMIT flags, verifies ALL non-absent
                             signatures (incentivization rule,
                             types/validation.go:19-25), 1:1 index lookup.
  verify_commit_light      — counts all non-ignored, stops at +2/3, 1:1 index.
  verify_commit_light_trusting — trust-fraction threshold, lookup by address
                             (valset may differ from the commit's), duplicate
                             detection.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable

from cometbft_tpu.crypto import batch as crypto_batch
from cometbft_tpu.types.basic import BlockID, BlockIDFlag
from cometbft_tpu.types.commit import Commit, CommitSig
from cometbft_tpu.types.validator import ValidatorSet

BATCH_VERIFY_THRESHOLD = 2  # types/validation.go:13


@dataclass(frozen=True)
class Fraction:
    """libs/math Fraction — light-client trust level."""

    numerator: int
    denominator: int


class ErrNotEnoughVotingPowerSigned(Exception):
    def __init__(self, got: int, needed: int):
        super().__init__(f"invalid commit -- insufficient voting power: got {got}, needed more than {needed}")
        self.got = got
        self.needed = needed


class ErrInvalidCommitSignature(Exception):
    pass


def _verify_basic(vals: ValidatorSet, commit: Commit, height: int, block_id: BlockID) -> None:
    """types/validation.go verifyBasicValsAndCommit."""
    if vals is None or vals.is_nil_or_empty():
        raise ValueError("nil or empty validator set")
    if commit is None:
        raise ValueError("nil commit")
    if len(vals) != len(commit.signatures):
        raise ValueError(
            f"invalid commit -- wrong set size: {len(vals)} vs {len(commit.signatures)}"
        )
    if height != commit.height:
        raise ValueError(f"invalid commit -- wrong height: {height} vs {commit.height}")
    if block_id != commit.block_id:
        raise ValueError(
            f"invalid commit -- wrong block ID: want {block_id}, got {commit.block_id}"
        )


def _should_batch_verify(vals: ValidatorSet, commit: Commit) -> bool:
    return len(commit.signatures) >= BATCH_VERIFY_THRESHOLD and crypto_batch.supports_batch_verifier(
        vals.get_proposer().pub_key if vals.get_proposer() else None
    )


def _commit_rows(
    chain_id: str,
    vals: ValidatorSet,
    commit: Commit,
    voting_power_needed: int,
    ignore_sig: Callable[[CommitSig], bool],
    count_sig: Callable[[CommitSig], bool],
    count_all_signatures: bool,
    lookup_by_index: bool,
) -> tuple[list, list[bytes], list[bytes], list[int]]:
    """The shared row-builder behind every batched commit verification
    (types/validation.go:153-257 loop body): select signatures, tally power,
    enforce the threshold. Returns (pubkeys, sign_bytes, sigs, commit_idxs);
    raises ErrNotEnoughVotingPowerSigned below threshold."""
    seen_vals: dict[int, int] = {}
    pubs: list = []
    sigs: list[bytes] = []
    idxs: list[int] = []
    tallied = 0
    sign_rows = commit.vote_sign_bytes_all(chain_id)
    # epoch-keyed device residency (reduced-send protocol): announce the
    # active validator set so the kernels' resident key tables pin its
    # rows and churn ships only deltas (ops/residency.py; never raises)
    try:
        from cometbft_tpu.ops import residency as _residency

        _residency.announce_validator_set(vals)
    except Exception:  # noqa: BLE001 - residency is an optimization layer
        pass
    for idx, cs in enumerate(commit.signatures):
        if ignore_sig(cs):
            continue
        if lookup_by_index:
            val = vals.validators[idx]
        else:
            val_idx, val = vals.get_by_address(cs.validator_address)
            if val is None:
                continue
            if val_idx in seen_vals:
                raise ValueError(
                    f"double vote from {val.address.hex()} ({seen_vals[val_idx]} and {idx})"
                )
            seen_vals[val_idx] = idx
        pubs.append(val.pub_key)
        sigs.append(cs.signature)
        idxs.append(idx)
        if count_sig(cs):
            tallied += val.voting_power
        if not count_all_signatures and tallied > voting_power_needed:
            break
    if tallied <= voting_power_needed:
        raise ErrNotEnoughVotingPowerSigned(got=tallied, needed=voting_power_needed)
    # factored (shared-prefix) rows when the builder supports them: the
    # staging fast path reassembles whole runs with one prefix broadcast
    # instead of N per-row copies (libs/prefixrows.py)
    if hasattr(sign_rows, "rows_for"):
        msgs = sign_rows.rows_for(idxs)
    else:
        msgs = [sign_rows[i] for i in idxs]
    return pubs, msgs, sigs, idxs


def _bls_aggregate_ok(pubs, msgs, sigs) -> bool | None:
    """The BLS aggregate commit path (ops/bls_kernel.aggregate_verify):
    when EVERY signer in the commit is a bls12381 key, the whole commit
    decides with one pairing-product check — signatures sum to a single
    G2 point, pubkeys aggregate per distinct sign-bytes (PoP semantics),
    cost ~independent of committee size. Returns None when the commit is
    not BLS-shaped (callers fall through to per-lane batching), True on
    an accepted aggregate, False when the aggregate fails — the caller
    then re-runs the per-lane path to PINPOINT the offending signature
    (the aggregate check is a commit-level verdict, not a mask).

    Never raises on verification trouble: a device fault inside
    aggregate_verify already degrades to the exact CPU oracle."""
    if not pubs or any(p.type_() != "bls12381" for p in pubs):
        return None
    from cometbft_tpu.crypto import bls12381

    if not bls12381.enabled():
        # loud misconfiguration, same rule as crypto/batch
        raise crypto_batch.crypto.ErrInvalidKey(
            "bls12381 validator set but crypto.bls_enabled is off")
    from cometbft_tpu.libs.prefixrows import as_bytes
    from cometbft_tpu.ops import bls_kernel

    return bls_kernel.aggregate_verify(
        [p.bytes_() for p in pubs], [as_bytes(m) for m in msgs],
        [bytes(s) for s in sigs])


def _bls_aggregate_agg_ok(pubs, msgs, agg_sig) -> bool | None:
    """Certificate-path sibling of _bls_aggregate_ok: the G2 side
    arrives ALREADY aggregated (a CommitCertificate's 96 B signature)
    so the one-pairing check runs without a summing stage. Same
    contract: None when the set is not BLS-shaped, ErrInvalidKey loud
    when the set is BLS but the backend is off, True/False for the
    pairing verdict. Never raises on verification trouble — device
    faults degrade to the exact CPU oracle inside the kernel."""
    if not pubs or any(p.type_() != "bls12381" for p in pubs):
        return None
    from cometbft_tpu.crypto import bls12381

    if not bls12381.enabled():
        # loud misconfiguration, same rule as crypto/batch
        raise crypto_batch.crypto.ErrInvalidKey(
            "bls12381 validator set but crypto.bls_enabled is off")
    from cometbft_tpu.libs.prefixrows import as_bytes
    from cometbft_tpu.ops import bls_kernel

    return bls_kernel.aggregate_verify_agg(
        [p.bytes_() for p in pubs], [as_bytes(m) for m in msgs],
        bytes(agg_sig))


def _raise_first_bad(commit: Commit, idxs: list[int], mask) -> None:
    for i, sig_ok in enumerate(mask):
        if not sig_ok:
            idx = idxs[i]
            raise ErrInvalidCommitSignature(
                f"wrong signature (#{idx}): {commit.signatures[idx].signature.hex()}"
            )


def _verify_commit_batch(
    chain_id: str,
    vals: ValidatorSet,
    commit: Commit,
    voting_power_needed: int,
    ignore_sig: Callable[[CommitSig], bool],
    count_sig: Callable[[CommitSig], bool],
    count_all_signatures: bool,
    lookup_by_index: bool,
) -> None:
    """types/validation.go:153-257."""
    pubs, msgs, sigs, idxs = _commit_rows(
        chain_id, vals, commit, voting_power_needed,
        ignore_sig, count_sig, count_all_signatures, lookup_by_index,
    )
    # all-BLS validator set: one pairing-product check per commit; a
    # failed aggregate falls through to the per-lane path to pinpoint
    if _bls_aggregate_ok(pubs, msgs, sigs):
        return
    # mixed-scheme coalescing: each key type becomes one device sub-batch
    # (BASELINE config 5 mega-commits mix ed25519 + sr25519 validators)
    bv = crypto_batch.create_mixed_batch_verifier()
    try:
        for pub, msg, sig in zip(pubs, msgs, sigs):
            bv.add(pub, msg, sig)
    except Exception as e:  # noqa: BLE001 - unbatchable key type in the set
        from cometbft_tpu.libs import log as _log

        _log.default().info(
            "commit verification falling back to serial", reason=str(e))
        return _verify_commit_single(
            chain_id, vals, commit, voting_power_needed,
            ignore_sig, count_sig, count_all_signatures, lookup_by_index,
        )
    ok, valid_sigs = bv.verify()
    if ok:
        return
    _raise_first_bad(commit, idxs, valid_sigs)
    raise RuntimeError("BUG: batch verification failed with no invalid signatures")


def _verify_commit_single(
    chain_id: str,
    vals: ValidatorSet,
    commit: Commit,
    voting_power_needed: int,
    ignore_sig: Callable[[CommitSig], bool],
    count_sig: Callable[[CommitSig], bool],
    count_all_signatures: bool,
    lookup_by_index: bool,
) -> None:
    """types/validation.go:266-330."""
    seen_vals: dict[int, int] = {}
    tallied = 0
    for idx, cs in enumerate(commit.signatures):
        if ignore_sig(cs):
            continue
        if lookup_by_index:
            val = vals.validators[idx]
        else:
            val_idx, val = vals.get_by_address(cs.validator_address)
            if val is None:
                continue
            if val_idx in seen_vals:
                raise ValueError(
                    f"double vote from {val.address.hex()} ({seen_vals[val_idx]} and {idx})"
                )
            seen_vals[val_idx] = idx
        sign_bytes = commit.vote_sign_bytes(chain_id, idx)
        if not val.pub_key.verify_signature(sign_bytes, cs.signature):
            raise ErrInvalidCommitSignature(
                f"wrong signature (#{idx}): {cs.signature.hex()}"
            )
        if count_sig(cs):
            tallied += val.voting_power
        if not count_all_signatures and tallied > voting_power_needed:
            return
    if tallied <= voting_power_needed:
        raise ErrNotEnoughVotingPowerSigned(got=tallied, needed=voting_power_needed)


def verify_commit(
    chain_id: str, vals: ValidatorSet, block_id: BlockID, height: int, commit: Commit
) -> None:
    """+2/3 signed; checks ALL signatures (types/validation.go:26-57)."""
    _verify_basic(vals, commit, height, block_id)
    needed = vals.total_voting_power() * 2 // 3

    def ignore(c: CommitSig) -> bool:
        return c.block_id_flag == BlockIDFlag.ABSENT

    def count(c: CommitSig) -> bool:
        return c.block_id_flag == BlockIDFlag.COMMIT

    if _should_batch_verify(vals, commit):
        _verify_commit_batch(chain_id, vals, commit, needed, ignore, count, True, True)
    else:
        _verify_commit_single(chain_id, vals, commit, needed, ignore, count, True, True)


def verify_commit_light(
    chain_id: str, vals: ValidatorSet, block_id: BlockID, height: int, commit: Commit
) -> None:
    """+2/3 signed; stops early (types/validation.go:60-92)."""
    _verify_basic(vals, commit, height, block_id)
    needed = vals.total_voting_power() * 2 // 3

    def ignore(c: CommitSig) -> bool:
        return c.block_id_flag != BlockIDFlag.COMMIT

    def count(c: CommitSig) -> bool:
        return True

    if _should_batch_verify(vals, commit):
        _verify_commit_batch(chain_id, vals, commit, needed, ignore, count, False, True)
    else:
        _verify_commit_single(chain_id, vals, commit, needed, ignore, count, False, True)


def verify_commit_light_trusting(
    chain_id: str, vals: ValidatorSet, commit: Commit, trust_level: Fraction
) -> None:
    """trustLevel of the (possibly different) valset signed
    (types/validation.go:95-131)."""
    if vals is None:
        raise ValueError("nil validator set")
    if trust_level.denominator == 0:
        raise ValueError("trustLevel has zero Denominator")
    if commit is None:
        raise ValueError("nil commit")
    needed = vals.total_voting_power() * trust_level.numerator // trust_level.denominator

    def ignore(c: CommitSig) -> bool:
        return c.block_id_flag != BlockIDFlag.COMMIT

    def count(c: CommitSig) -> bool:
        return True

    if _should_batch_verify(vals, commit):
        _verify_commit_batch(chain_id, vals, commit, needed, ignore, count, False, False)
    else:
        _verify_commit_single(chain_id, vals, commit, needed, ignore, count, False, False)


# ---------------------------------------------------------------------------
# Streaming (async) commit verification — the blocksync/light-client seam.
#
# The reference verifies each commit synchronously, twice (VerifyCommitLight
# in the blocksync reactor, then VerifyCommit again inside validateBlock,
# blocksync/reactor.go:463 + state/validation.go:92). TPU-first redesign:
# stage ONE full-semantics verification per commit on the device without
# blocking (verify_batch_async), resolve a whole window of heights with a
# single device fetch (resolve_batches), and let ApplyBlock skip the
# redundant re-verification (last_commit_verified).
# ---------------------------------------------------------------------------


class StagedCommitVerification:
    """A staged-but-unresolved verify_commit: finish() raises exactly what
    the sync path would. On the TPU backend the prepared rows (ed_rows) are
    NOT dispatched at staging time — prefetch_staged coalesces every staged
    commit in a window into ONE device batch (one transfer, one kernel
    dispatch, one device->host fetch), which is what makes the blocksync
    window pipeline device-bound instead of dispatch-overhead-bound.
    device_thunk remains supported for callers that pre-dispatched."""

    def __init__(self, commit: Commit, sig_idxs: list[int], device_thunk=None,
                 cpu_rows=None, ed_rows=None, bls_rows=None):
        self.commit = commit
        self.sig_idxs = sig_idxs
        self.device_thunk = device_thunk
        self._cpu_rows = cpu_rows
        self._ed_rows = ed_rows  # (pub_bytes, msgs, sigs) all-ed25519 rows
        # (pubs, msgs, sigs) all-bls12381 rows: finish() tries ONE
        # aggregate pairing-product check first; only a failed aggregate
        # pays the per-lane pinpoint pass
        self._bls_rows = bls_rows
        self._mask = None
        self._passed = False

    def finish(self, mask=None) -> None:
        """Materialize the mask (or use the window-resolved one) and apply
        the reference error semantics: first invalid signature raises.
        Idempotent once passed (a caller may finish early for ordering and
        again after a window prefetch)."""
        if self._passed:
            return
        if mask is None:
            mask = self._mask
        if mask is None and self._bls_rows is not None:
            pubs, msgs, sigs = self._bls_rows
            if _bls_aggregate_ok(pubs, msgs, sigs):
                self._passed = True
                return
            # pinpoint below through the per-lane batch path
            self._cpu_rows = self._bls_rows
        if mask is None:
            if self.device_thunk is not None:
                mask = self.device_thunk()
            elif self._ed_rows is not None:
                # solo finish without a window prefetch: dispatch this
                # commit's rows as their own device batch
                from cometbft_tpu.ops import ed25519_kernel

                mask = ed25519_kernel.verify_batch_async(*self._ed_rows)()
            else:
                # non-ed25519 / non-TPU rows: still batched per scheme (the
                # mixed verifier reaches the sr25519 device kernel on the
                # TPU backend) rather than serial per-signature host calls
                pubs, msgs, sigs = self._cpu_rows
                bv = crypto_batch.create_mixed_batch_verifier()
                try:
                    for p, m, s in zip(pubs, msgs, sigs):
                        bv.add(p, m, s)
                    _, mask = bv.verify()
                except Exception:  # noqa: BLE001 - unbatchable key type
                    from cometbft_tpu.libs.prefixrows import as_bytes

                    # materialize factored rows: schemes outside the
                    # batch registry (secp256k1) take raw bytes only
                    mask = [p.verify_signature(as_bytes(m), s)
                            for p, m, s in zip(pubs, msgs, sigs)]
        _raise_first_bad(self.commit, self.sig_idxs, mask)
        self._passed = True


def _stage_rows(commit: Commit, rows) -> StagedCommitVerification:
    """Prepare commit rows for the device batch when every key is ed25519
    on the TPU backend (dispatch deferred to prefetch_staged / finish);
    else defer to per-scheme host batching at finish()."""
    pubs, msgs, sigs, idxs = rows
    if pubs and all(p.type_() == "bls12381" for p in pubs):
        # aggregate-verified at finish(): blocksync/light windows decide
        # each BLS commit with one pairing-product check
        return StagedCommitVerification(
            commit, idxs, bls_rows=(pubs, msgs, sigs))
    if crypto_batch.resolve_backend() == "tpu" and all(
        p.type_() == "ed25519" for p in pubs
    ):
        return StagedCommitVerification(
            commit, idxs, ed_rows=([p.bytes_() for p in pubs], msgs, sigs))
    return StagedCommitVerification(commit, idxs, cpu_rows=(pubs, msgs, sigs))


def stage_verify_commit(
    chain_id: str, vals: ValidatorSet, block_id: BlockID, height: int, commit: Commit
) -> StagedCommitVerification:
    """verify_commit (full semantics: every non-absent signature checked,
    COMMIT flags tallied, types/validation.go:26-57) staged asynchronously.
    Structural checks + the voting-power threshold run here, synchronously;
    signature validity is deferred to .finish()."""
    _verify_basic(vals, commit, height, block_id)
    needed = vals.total_voting_power() * 2 // 3
    rows = _commit_rows(
        chain_id, vals, commit, needed,
        ignore_sig=lambda c: c.block_id_flag == BlockIDFlag.ABSENT,
        count_sig=lambda c: c.block_id_flag == BlockIDFlag.COMMIT,
        count_all_signatures=True,
        lookup_by_index=True,
    )
    return _stage_rows(commit, rows)


def stage_verify_commit_light(
    chain_id: str, vals: ValidatorSet, block_id: BlockID, height: int, commit: Commit
) -> StagedCommitVerification:
    """verify_commit_light staged: the light client's +2/3-of-new-set check
    (types/validation.go:60-92), deferred so a bisection hop's two checks
    resolve with ONE device fetch."""
    _verify_basic(vals, commit, height, block_id)
    needed = vals.total_voting_power() * 2 // 3
    rows = _commit_rows(
        chain_id, vals, commit, needed,
        ignore_sig=lambda c: c.block_id_flag != BlockIDFlag.COMMIT,
        count_sig=lambda c: True,
        count_all_signatures=False,
        lookup_by_index=True,
    )
    return _stage_rows(commit, rows)


def stage_verify_commit_light_trusting(
    chain_id: str, vals: ValidatorSet, commit: Commit, trust_level: Fraction
) -> StagedCommitVerification:
    """verify_commit_light_trusting staged (types/validation.go:95-131).
    The voting-power threshold (raising ErrNotEnoughVotingPowerSigned)
    runs here synchronously; signature validity at finish()."""
    if vals is None:
        raise ValueError("nil validator set")
    if trust_level.denominator == 0:
        raise ValueError("trustLevel has zero Denominator")
    if commit is None:
        raise ValueError("nil commit")
    needed = vals.total_voting_power() * trust_level.numerator // trust_level.denominator
    rows = _commit_rows(
        chain_id, vals, commit, needed,
        ignore_sig=lambda c: c.block_id_flag != BlockIDFlag.COMMIT,
        count_sig=lambda c: True,
        count_all_signatures=False,
        lookup_by_index=False,
    )
    return _stage_rows(commit, rows)


def prefetch_staged(staged: list[StagedCommitVerification],
                    klass: str | None = None) -> None:
    """Resolve every staged commit in the window with ONE device batch:
    the window's rows concatenate into a single transfer + kernel dispatch +
    device->host fetch, then the combined mask is sliced back per commit.
    The fetch rides the reduced-fetch protocol (ed25519_kernel.
    resolve_batches): a happy window — every commit valid, the steady
    state — transfers 8 bytes per batch; the per-lane masks are pulled
    only when some batch's header reports a failure. Subsequent finish()
    calls are pure host work (per-commit error isolation stays with the
    caller). Pre-dispatched device_thunk items are resolved alongside with
    the same single fetch.

    With the global verify scheduler enabled (the default) the window is
    submitted to it instead — one group per commit, so each keeps its own
    host-oracle recheck budget — under `klass` (default SYNC: blocksync
    and light-client windows yield the device to consensus flushes), and
    queued mempool-admission work rides the same batch as filler."""
    from cometbft_tpu import sched

    if sched.enabled():
        _prefetch_via_scheduler(staged, klass or sched.SYNC)
        return
    from cometbft_tpu.ops import ed25519_kernel

    rows = [s for s in staged
            if s._ed_rows is not None and s._mask is None and not s._passed]
    pre = [s for s in staged
           if s.device_thunk is not None and s._mask is None
           and not s._passed]
    thunks = [s.device_thunk for s in pre]
    # chunk the combined batch below the kernel's lane cap (chunks aligned
    # to commit boundaries; a single commit is bounded by the 10k-validator
    # cap). All chunks still resolve with the one fetch below.
    chunk_cap = 1 << (ed25519_kernel.MAX_BUCKET_LOG2 - 1)
    chunks: list[list[StagedCommitVerification]] = []
    cur: list[StagedCommitVerification] = []
    cur_n = 0
    for s in rows:
        n = len(s._ed_rows[2])
        if cur and cur_n + n > chunk_cap:
            chunks.append(cur)
            cur, cur_n = [], 0
        cur.append(s)
        cur_n += n
    if cur:
        chunks.append(cur)
    n_pre = len(thunks)
    for chunk in chunks:
        pubs: list[bytes] = []
        msgs: list[bytes] = []
        sigs: list[bytes] = []
        groups: list[tuple[int, int]] = []
        for s in chunk:
            p, m, g = s._ed_rows
            groups.append((len(sigs), len(sigs) + len(g)))
            pubs.extend(p)
            msgs.extend(m)
            sigs.extend(g)
        thunks.append(ed25519_kernel.verify_batch_async(
            pubs, msgs, sigs, recheck_groups=groups))
    if not thunks:
        return
    resolved = ed25519_kernel.resolve_batches(thunks)
    for chunk, combined in zip(chunks, resolved[n_pre:]):
        off = 0
        for s in chunk:
            n = len(s._ed_rows[2])
            s._mask = combined[off:off + n]
            off += n
    for s, m in zip(pre, resolved[:n_pre]):
        s._mask = m


def _prefetch_via_scheduler(staged: list[StagedCommitVerification],
                            klass: str) -> None:
    """Scheduler-side window resolution: every unresolved staged commit
    (device-staged ed rows AND host-staged cpu rows — the scheduler picks
    the backend per dispatch, so a CPU-backend window still coalesces)
    becomes one scheduler group; pre-dispatched device thunks resolve
    alongside through the kernel fetch path as before."""
    from cometbft_tpu import sched
    from cometbft_tpu.ops import ed25519_kernel

    pre = [s for s in staged
           if s.device_thunk is not None and s._mask is None and not s._passed]
    todo: list[StagedCommitVerification] = []
    rowlists: list[list] = []
    for s in staged:
        if s._passed or s._mask is not None or s.device_thunk is not None:
            continue
        if getattr(s, "_bls_rows", None) is not None:
            continue  # aggregate-verified at finish(), one check total
        if s._ed_rows is not None:
            from cometbft_tpu.crypto import ed25519 as _ed

            pubs_b, msgs, sigs = s._ed_rows
            rows = [(_ed.PubKey(p), m, g)
                    for p, m, g in zip(pubs_b, msgs, sigs)]
        elif s._cpu_rows is not None:
            pubs, msgs, sigs = s._cpu_rows
            rows = list(zip(pubs, msgs, sigs))
        else:
            continue
        todo.append(s)
        rowlists.append(rows)
    if rowlists:
        masks = sched.get().verify_many(rowlists, klass)
        for s, mask in zip(todo, masks):
            s._mask = mask
    if pre:
        resolved = ed25519_kernel.resolve_batches([s.device_thunk for s in pre])
        for s, m in zip(pre, resolved):
            s._mask = m


def resolve_staged(staged: list[StagedCommitVerification]) -> None:
    """Finish a window of staged verifications with one device fetch.
    Raises on the first bad commit, in window order."""
    prefetch_staged(staged)
    for s in staged:
        s.finish()
