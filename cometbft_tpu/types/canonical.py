"""Canonical sign-bytes — byte-exact with the reference's gogoproto output.

The CanonicalVote/CanonicalProposal/CanonicalVoteExtension encodings
(reference: types/canonical.go, proto/tendermint/types/canonical.proto,
generated marshal canonical.pb.go:590-640) are THE interop surface: every
signature in the system is over these bytes, varint-length-delimited
(libs/protoio/writer.go:93). Field rules confirmed against the generated
marshaller:
  - type:    varint, omitted when 0
  - height:  sfixed64, omitted when 0
  - round:   sfixed64, omitted when 0   (int64 of the int32 round)
  - block_id: nullable message — omitted when the BlockID is nil/zero
  - timestamp: ALWAYS emitted (gogoproto non-nullable stdtime)
  - chain_id: omitted when empty
"""

from __future__ import annotations

from cometbft_tpu.types.basic import BlockID, SignedMsgType
from cometbft_tpu.utils import cmttime
from cometbft_tpu.utils import protobuf as pb


def canonical_block_id_bytes(block_id: BlockID) -> bytes | None:
    """CanonicalBlockID: hash=1, part_set_header=2 non-nullable.
    Returns None for nil block IDs (field omitted, types/canonical.go:18-34)."""
    if block_id.is_nil():
        return None
    w = pb.Writer()
    w.bytes(1, block_id.hash)
    w.message(2, block_id.part_set_header.to_proto(), always=True)
    return w.output()


def _timestamp(ts: cmttime.Timestamp) -> bytes:
    return pb.timestamp_bytes(ts.seconds, ts.nanos)


def vote_sign_bytes(
    chain_id: str,
    msg_type: SignedMsgType,
    height: int,
    round_: int,
    block_id: BlockID,
    timestamp: cmttime.Timestamp,
) -> bytes:
    """CanonicalVote, length-delimited (types/vote.go:139, canonical.proto:30-37)."""
    w = pb.Writer()
    w.uvarint(1, int(msg_type))
    w.sfixed64(2, height)
    w.sfixed64(3, round_)
    w.message(4, canonical_block_id_bytes(block_id))
    w.message(5, _timestamp(timestamp), always=True)
    w.string(6, chain_id)
    return pb.marshal_delimited(w.output())


def proposal_sign_bytes(
    chain_id: str,
    height: int,
    round_: int,
    pol_round: int,
    block_id: BlockID,
    timestamp: cmttime.Timestamp,
) -> bytes:
    """CanonicalProposal (types/proposal.go ProposalSignBytes,
    canonical.proto:20-28). pol_round is plain varint int64; -1 when no POL."""
    w = pb.Writer()
    w.uvarint(1, int(SignedMsgType.PROPOSAL))
    w.sfixed64(2, height)
    w.sfixed64(3, round_)
    w.varint_i64(4, pol_round)
    w.message(5, canonical_block_id_bytes(block_id))
    w.message(6, _timestamp(timestamp), always=True)
    w.string(7, chain_id)
    return pb.marshal_delimited(w.output())


def vote_extension_sign_bytes(
    chain_id: str, height: int, round_: int, extension: bytes
) -> bytes:
    """CanonicalVoteExtension (types/vote.go VoteExtensionSignBytes,
    canonical.proto:41-46)."""
    w = pb.Writer()
    w.bytes(1, extension)
    w.sfixed64(2, height)
    w.sfixed64(3, round_)
    w.string(4, chain_id)
    return pb.marshal_delimited(w.output())
