"""Headline benchmark: Ed25519 signatures verified per second per chip.

Reproduces BASELINE.json config 1/3/5 shape: a stream of 10k-signature
mega-batches (the 10k-validator commit cap, types/vote_set.go:17) pushed
through the TPU batch-verification pipeline end-to-end — host staging
(SHA-512 challenges, packed-word layout), device kernel (Pallas fused
ladder), mask readback — with the device-resident pubkey cache warm (a
validator set re-verifies every height; the reference's expanded-key LRU
plays the same role, crypto/ed25519/ed25519.go:44).

Two numbers:
  * streaming throughput (HEADLINE): N batches dispatched back-to-back
    with async readback — the blocksync catch-up shape (BASELINE config 3),
    host staging of batch i+1 overlapped with device verify of batch i.
  * p50 single-batch latency: one synchronous verify_batch call. NOTE:
    this dev box reaches its TPU through a network tunnel with an ~89 ms
    round-trip floor and ~22 MB/s bandwidth; single-call latency is
    tunnel-bound, not kernel-bound (device compute is ~31 ms/10k sigs).

Baseline: serial OpenSSL single-verify on this host's one CPU core —
the best CPU verifier available in this image (no Go toolchain, so the
reference's curve25519-voi batch verifier, ed25519.go:208-241, cannot be
run here; public numbers put it at roughly 3-4x serial OpenSSL on one
core, which would still leave the TPU path >10x ahead).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

from __future__ import annotations

import json
import os
import secrets
import sys
import time

os.environ.setdefault("XLA_FLAGS", "")

BATCH = int(os.environ.get("BENCH_BATCH", "10240"))
CPU_SAMPLE = int(os.environ.get("BENCH_CPU_SAMPLE", "2048"))
ITERS = int(os.environ.get("BENCH_ITERS", "5"))
STREAM_BATCHES = int(os.environ.get("BENCH_STREAM_BATCHES", "16"))


def main() -> None:
    import jax

    jax.config.update("jax_compilation_cache_dir", os.path.join(os.path.dirname(__file__), ".jax_cache"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 2)

    from cometbft_tpu.crypto import ed25519
    from cometbft_tpu.ops import ed25519_kernel as K

    # -- build the batch: one "validator set" signing distinct messages
    n_vals = min(BATCH, 10240)
    privs = [ed25519.gen_priv_key() for _ in range(n_vals)]
    pubs, msgs, sigs = [], [], []
    for i in range(BATCH):
        p = privs[i % n_vals]
        msg = b"bench-vote-" + i.to_bytes(4, "big") + secrets.token_bytes(8)
        pubs.append(p.pub_key().bytes_())
        msgs.append(msg)
        sigs.append(p.sign(msg))

    cache = K.PubKeyCache()
    # warm-up: compiles the kernel and fills the pubkey cache
    ok, _ = K.verify_batch(pubs, msgs, sigs, cache=cache)
    assert ok, "warm-up batch failed verification"

    # -- p50 synchronous single-batch latency
    lat = []
    for _ in range(ITERS):
        t0 = time.perf_counter()
        ok, mask = K.verify_batch(pubs, msgs, sigs, cache=cache)
        lat.append(time.perf_counter() - t0)
        assert ok
    p50_latency = sorted(lat)[len(lat) // 2]

    # -- streaming throughput: async dispatch, one sync point at the end
    #    (the blocksync catch-up shape: every height's commit re-verified
    #    against the same validator set)
    t0 = time.perf_counter()
    thunks = [
        K.verify_batch_async(pubs, msgs, sigs, cache=cache)
        for _ in range(STREAM_BATCHES)
    ]
    results = K.resolve_batches(thunks)
    t_stream = time.perf_counter() - t0
    assert all(m.all() for m in results)
    tpu_sigs_per_s = STREAM_BATCHES * BATCH / t_stream

    # -- CPU baseline: serial OpenSSL loop on a sample, extrapolated
    sample = CPU_SAMPLE
    pk_objs = [ed25519.PubKey(pubs[i]) for i in range(sample)]
    t0 = time.perf_counter()
    for i in range(sample):
        assert pk_objs[i].verify_signature(msgs[i], sigs[i])
    t_cpu = time.perf_counter() - t0
    cpu_sigs_per_s = sample / t_cpu

    print(
        json.dumps(
            {
                "metric": "ed25519_verify_throughput",
                "value": round(tpu_sigs_per_s, 1),
                "unit": "sigs/sec/chip",
                "vs_baseline": round(tpu_sigs_per_s / cpu_sigs_per_s, 2),
                "detail": {
                    "batch": BATCH,
                    "stream_batches": STREAM_BATCHES,
                    "p50_batch_latency_ms": round(p50_latency * 1e3, 2),
                    "tunnel_note": "single-batch latency includes ~89ms axon-tunnel RTT floor",
                    "cpu_baseline_sigs_per_s": round(cpu_sigs_per_s, 1),
                    "cpu_baseline": "serial OpenSSL, 1 core (this host's only core; no Go toolchain for the reference batch verifier)",
                    "backend": jax.devices()[0].platform,
                },
            }
        )
    )


if __name__ == "__main__":
    sys.exit(main())
