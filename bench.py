"""Headline benchmark: Ed25519 signatures verified per second per chip.

Reproduces BASELINE.json config 1/5 shape: a mega-batch of random signatures
(default 10240 ~ the 10k-validator commit cap, types/vote_set.go:17) pushed
through the TPU batch-verification pipeline end-to-end — host staging
(SHA-512 challenges, limb packing), device kernel, mask readback — with the
decompressed-pubkey cache warm (a validator set re-verifies every height;
the reference's expanded-key LRU plays the same role,
crypto/ed25519/ed25519.go:44).

Baseline: the CPU serial path (OpenSSL, same machine) — the stand-in for the
reference's Go batch verifier; vs_baseline is the throughput ratio.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

from __future__ import annotations

import json
import os
import secrets
import sys
import time

os.environ.setdefault("XLA_FLAGS", "")

BATCH = int(os.environ.get("BENCH_BATCH", "10240"))
CPU_SAMPLE = int(os.environ.get("BENCH_CPU_SAMPLE", "2048"))
ITERS = int(os.environ.get("BENCH_ITERS", "5"))


def main() -> None:
    import jax

    jax.config.update("jax_compilation_cache_dir", os.path.join(os.path.dirname(__file__), ".jax_cache"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 2)

    from cometbft_tpu.crypto import ed25519
    from cometbft_tpu.ops import ed25519_kernel as K

    # -- build the batch: one "validator set" signing distinct messages
    n_vals = min(BATCH, 10240)
    privs = [ed25519.gen_priv_key() for _ in range(n_vals)]
    pubs, msgs, sigs = [], [], []
    for i in range(BATCH):
        p = privs[i % n_vals]
        msg = b"bench-vote-" + i.to_bytes(4, "big") + secrets.token_bytes(8)
        pubs.append(p.pub_key().bytes_())
        msgs.append(msg)
        sigs.append(p.sign(msg))

    cache = K.PubKeyCache()
    # warm-up: compiles the kernel and fills the pubkey cache
    ok, _ = K.verify_batch(pubs, msgs, sigs, cache=cache)
    assert ok, "warm-up batch failed verification"

    times = []
    for _ in range(ITERS):
        t0 = time.perf_counter()
        ok, mask = K.verify_batch(pubs, msgs, sigs, cache=cache)
        times.append(time.perf_counter() - t0)
        assert ok
    t_device = min(times)
    tpu_sigs_per_s = BATCH / t_device

    # -- CPU baseline: serial OpenSSL loop on a sample, extrapolated
    sample = CPU_SAMPLE
    pk_objs = [ed25519.PubKey(pubs[i]) for i in range(sample)]
    t0 = time.perf_counter()
    for i in range(sample):
        assert pk_objs[i].verify_signature(msgs[i], sigs[i])
    t_cpu = time.perf_counter() - t0
    cpu_sigs_per_s = sample / t_cpu

    print(
        json.dumps(
            {
                "metric": "ed25519_verify_throughput",
                "value": round(tpu_sigs_per_s, 1),
                "unit": "sigs/sec/chip",
                "vs_baseline": round(tpu_sigs_per_s / cpu_sigs_per_s, 2),
                "detail": {
                    "batch": BATCH,
                    "p50_batch_latency_ms": round(sorted(times)[len(times) // 2] * 1e3, 2),
                    "cpu_baseline_sigs_per_s": round(cpu_sigs_per_s, 1),
                    "backend": jax.devices()[0].platform,
                },
            }
        )
    )


if __name__ == "__main__":
    sys.exit(main())
