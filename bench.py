"""Headline benchmark: Ed25519 signatures verified per second per chip.

Reproduces BASELINE.json shapes on the real device:
  config 1/5 — a stream of 10k-signature mega-batches (the 10k-validator
    commit cap, types/vote_set.go:17) through the TPU pipeline end-to-end
    with the device pubkey cache warm. HEADLINE: streaming sigs/s/chip.
  config 3 — blocksync catch-up: 1,000 consecutive 150-validator commits
    through the windowed stage/prefetch pipeline (types/validation.py,
    blocksync/reactor.py shape): blocks/s + device busy fraction.
  config 4 — light-client bisection across a simulated 100k-height,
    500-validator chain with valset churn (every hop's commit checks ride
    the device batch verifier).
  consensus-on-TPU — a 4-validator in-process net with the batched vote
    path flushing through the REAL device (tests force the CPU backend;
    this is the latency evidence VERDICT r2 item 8 asked for).

Baselines (both reported):
  vs_serial — measured serial OpenSSL single-verify on this host's core.
  vs_batch_pinned — serial extrapolated by a PINNED 4x batch-speedup
    factor for the reference's curve25519-voi batch verifier
    (crypto/ed25519/ed25519.go:208-241). No Go toolchain exists in this
    image to measure it directly; published curve25519-voi/ed25519-dalek
    batch-verification numbers sit at ~2-3x serial on one core, so 4x is
    a deliberately conservative (baseline-favoring) bound.

NOTE: this dev box reaches its TPU through a network tunnel (~89 ms RTT
floor, ~22 MB/s). Single-batch p50 latency is tunnel-bound; the
device_compute_ms figure isolates kernel time by rep-differencing (time
of k+N chained kernels minus time of k, over N).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

from __future__ import annotations

import json
import os
import secrets
import sys
import time

os.environ.setdefault("XLA_FLAGS", "")

BATCH = int(os.environ.get("BENCH_BATCH", "10240"))
CPU_SAMPLE = int(os.environ.get("BENCH_CPU_SAMPLE", "2048"))
ITERS = int(os.environ.get("BENCH_ITERS", "5"))
STREAM_BATCHES = int(os.environ.get("BENCH_STREAM_BATCHES", "16"))
BS_HEIGHTS = int(os.environ.get("BENCH_BS_HEIGHTS", "1000"))
BS_VALS = int(os.environ.get("BENCH_BS_VALS", "150"))
LC_HEIGHT = int(os.environ.get("BENCH_LC_HEIGHT", "100000"))
LC_VALS = int(os.environ.get("BENCH_LC_VALS", "500"))
# light-client fleet serving scenario (bench_light_fleet)
FLEET_CLIENTS = int(os.environ.get("BENCH_FLEET_CLIENTS", "10000"))
FLEET_HEIGHT = int(os.environ.get("BENCH_FLEET_HEIGHT", "20000"))
FLEET_VALS = int(os.environ.get("BENCH_FLEET_VALS", "64"))
MIXED_BATCH = int(os.environ.get("BENCH_MIXED", "10240"))
PINNED_VOI_BATCH_FACTOR = 4.0
VS_BATCH_NOTE = (
    "serial OpenSSL x pinned 4.0 factor for curve25519-voi batch verify "
    "(published numbers ~2-3x; 4x chosen to favor the baseline)"
)


def _progress(msg: str) -> None:
    """Stage progress on stderr (the driver parses stdout's single JSON
    line; stderr shows where a run is if it stalls)."""
    print(f"[bench +{time.perf_counter() - _T0:7.1f}s] {msg}",
          file=sys.stderr, flush=True)


_T0 = time.perf_counter()


def _mk_sigs(n, n_keys):
    from cometbft_tpu.crypto import ed25519

    privs = [ed25519.gen_priv_key() for _ in range(n_keys)]
    pubs, msgs, sigs = [], [], []
    for i in range(n):
        p = privs[i % n_keys]
        msg = b"bench-vote-" + i.to_bytes(4, "big") + secrets.token_bytes(8)
        pubs.append(p.pub_key().bytes_())
        msgs.append(msg)
        sigs.append(p.sign(msg))
    return privs, pubs, msgs, sigs


_run_n_cache: dict = {}


def _get_run_n(verify_fn):
    """One jitted repeat-runner per verify program: a fresh closure per
    timing call would miss the in-process jit cache and re-enter the
    compile path (tunnel-expensive) on every retry."""
    fn = _run_n_cache.get(verify_fn)
    if fn is None:
        import functools

        import jax
        import jax.numpy as jnp

        @functools.partial(jax.jit, static_argnames=("reps",))
        def run_n(ax, ay, az, at, rw, sw, kw, reps=1):
            acc = jnp.zeros((), jnp.int32)
            for i in range(reps):
                acc = acc + verify_fn(
                    ax, ay, az, at, rw, sw + jnp.uint32(i), kw).sum()
            return acc

        fn = _run_n_cache[verify_fn] = run_n
    return fn


def bench_device_compute(verify_fn, a_dev, rwd, swd, kwd,
                         rep_pair=(2, 8)) -> float:
    """Kernel-only ms per batch via rep-differencing through the tunnel.
    rep_pair must put enough device work between the two points to clear
    the tunnel noise — small batches need a wide pair like (8, 64).
    verify_fn: the per-chip verify program (Pallas or XLA path)."""
    run_n = _get_run_n(verify_fn)
    lo, hi = rep_pair
    out = {}
    for reps in rep_pair:
        run_n(*a_dev, rwd, swd, kwd, reps=reps).block_until_ready()
        ts = []
        for _ in range(4):
            t0 = time.perf_counter()
            run_n(*a_dev, rwd, swd, kwd, reps=reps).block_until_ready()
            ts.append(time.perf_counter() - t0)
        out[reps] = min(ts)
    return (out[hi] - out[lo]) / (hi - lo) * 1e3


def _run_stats(runs: list[float], converged: bool) -> dict:
    """Honest spread over ALL post-warmup runs: median + p90 +
    spread_pct ((p90 - min) / min). The old artifact reported min-vs-min
    agreement as 'repeatability', which hid bimodal run lists like
    [2.08, 8.63, 8.53, 8.66, 8.5, 1.99] behind a 4.3% figure."""
    s = sorted(runs)
    n = len(s)
    median = s[n // 2] if n % 2 else (s[n // 2 - 1] + s[n // 2]) / 2
    p90 = s[min(n - 1, int(0.9 * (n - 1) + 0.999))]
    return {
        "runs": n,
        "min_ms": round(s[0], 2),
        "median_ms": round(median, 2),
        "p90_ms": round(p90, 2),
        "spread_pct": round((p90 - s[0]) / s[0] * 100, 1) if n > 1 else None,
        "best_pair_converged": converged,
    }


def measure_device_compute(verify_fn, a_dev, rwd, swd, kwd, rep_pair=(2, 8),
                           tol_pct=10.0, max_tries=6, budget_s=240.0):
    """Defensible device-compute time: rep-difference repeatedly until the
    two SMALLEST runs agree within tol_pct (dev-box contention only ever
    inflates a slope, so the two quietest runs bracket the true kernel
    time), refusing non-positive slopes (a too-narrow pair under tunnel
    noise). Returns (best_ms, runs_ms, stats): best is the min of the two
    converged quietest runs (the defensible kernel-time claim), while
    `stats` reports the HONEST spread over every post-warmup run —
    median + p90 + spread_pct (_run_stats) — identically for every scheme
    that calls this. A spread far above tol_pct means the box was noisy or
    the measurement bimodal; both are recorded as-is so the artifact is
    honest about its own quality. Raises only if no positive slope was
    ever measured."""
    runs: list[float] = []
    pair = rep_pair
    converged = False
    deadline = time.perf_counter() + budget_s  # contention must not stall
    for _ in range(max_tries):
        if time.perf_counter() > deadline and runs:
            break
        ms = bench_device_compute(verify_fn, a_dev, rwd, swd, kwd, pair)
        if ms <= 0:
            # widen: more device work between the two points (capped — a
            # runaway widening loop under heavy box contention must not
            # stall the whole bench; each retry also consumes a try)
            pair = (pair[0], min(pair[1] * 2, 64))
            continue
        runs.append(ms)
        if len(runs) >= 2:
            lo2 = sorted(runs)[:2]
            if (lo2[1] - lo2[0]) / lo2[0] * 100 <= tol_pct:
                converged = True
                break
    if not runs:
        raise RuntimeError(
            f"no positive slope after {max_tries} tries (pair widened to {pair})")
    return (min(runs), [round(r, 2) for r in runs],
            _run_stats(runs, converged))


def bench_blocksync(detail: dict) -> None:
    """BASELINE config 3: stream BS_HEIGHTS consecutive commits from a
    BS_VALS-validator chain through the stage/prefetch window pipeline —
    the exact device path blocksync's pool routine drives."""
    sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "tests"))
    from light_harness import LightChain

    from cometbft_tpu.types import validation

    chain = LightChain("bench-bs", BS_HEIGHTS + 1, n_vals=BS_VALS)
    vals = chain.valsets[1]
    window = 32
    heights = list(range(1, BS_HEIGHTS + 1))
    # warm the kernel for this bucket size (compile happens once per shape)
    lb1 = chain.blocks[1]
    warm = validation.stage_verify_commit(
        "bench-bs", vals, lb1.commit.block_id, 1, lb1.commit)
    validation.prefetch_staged([warm])

    def stage(hs):
        out = []
        for h in hs:
            lb = chain.blocks[h]
            out.append(validation.stage_verify_commit(
                "bench-bs", vals, lb.commit.block_id, h, lb.commit))
        return out

    # pipelined like blocksync._pool_routine: stage window N+1 on the host
    # while window N's masks are fetched from the device in a thread.
    # device_busy = time the fetch itself took (it overlaps host staging),
    # so the fraction reads "share of wall-clock the device was working".
    import concurrent.futures

    def timed_prefetch(batch):
        tb = time.perf_counter()
        validation.prefetch_staged(batch)
        return time.perf_counter() - tb

    ex = concurrent.futures.ThreadPoolExecutor(1)
    t0 = time.perf_counter()
    device_busy = 0.0
    done = 0
    staged = stage(heights[:window])
    while staged:
        fut = ex.submit(timed_prefetch, staged)
        nxt = done + len(staged)
        staged_next = stage(heights[nxt:nxt + window])
        device_busy += fut.result()
        for s in staged:
            s.finish()
        done = nxt
        staged = staged_next
    wall = time.perf_counter() - t0
    ex.shutdown()
    detail["blocksync_blocks_per_s"] = round(BS_HEIGHTS / wall, 1)
    detail["blocksync_sigs_per_s"] = round(BS_HEIGHTS * BS_VALS / wall, 1)
    detail["blocksync_device_busy_fraction"] = round(device_busy / wall, 3)
    detail["blocksync_shape"] = f"{BS_HEIGHTS} heights x {BS_VALS} validators, window {window}"
    detail["blocksync_note"] = (
        "busy fraction ~1.0 means wall time IS the device round-trip path "
        "(transfer + dispatch + fetch through the shared dev-box tunnel); "
        "host staging fully overlaps. Quiet-tunnel measurements of this "
        "pipeline reach ~240 blocks/s; a contended tunnel collapses the "
        "number with no code-path change (see tunnel_cap_note)")


def bench_mixed_megacommit(detail: dict) -> None:
    """BASELINE config 5: a mixed ed25519+sr25519 10k-validator mega-commit
    through MixedBatchVerifier — half the rows each scheme, one device batch
    per scheme, both dispatched async and resolved with one fetch. Reports
    wall latency (tunnel-inclusive), a host-staging/device/tunnel
    decomposition, and the sr25519 kernel's rep-differenced device time."""
    from cometbft_tpu.crypto import batch as crypto_batch
    from cometbft_tpu.crypto import ed25519, sr25519

    n_half = MIXED_BATCH // 2
    ed_keys = [ed25519.gen_priv_key() for _ in range(min(n_half, 1024))]
    sr_keys = [sr25519.gen_priv_key() for _ in range(min(n_half, 128))]
    rows = []
    for i in range(n_half):
        k = ed_keys[i % len(ed_keys)]
        m = b"mixed-ed-" + i.to_bytes(4, "big")
        rows.append((k.pub_key(), m, k.sign(m)))
    # sr25519 signing is ~5 ms/sig in the pure-Python schnorrkel host path;
    # sign 512 distinct rows and tile them — verification cost per lane is
    # content-independent, and the verifier recomputes every row's
    # challenge, so the measured verify() wall is not flattered
    distinct = []
    for i in range(min(n_half, 512)):
        k = sr_keys[i % len(sr_keys)]
        m = b"mixed-sr-" + i.to_bytes(4, "big")
        distinct.append((k.pub_key(), m, k.sign(m)))
    for i in range(n_half):
        rows.append(distinct[i % len(distinct)])

    def run() -> float:
        v = crypto_batch.MixedBatchVerifier()
        for pk, m, s in rows:
            v.add(pk, m, s)
        t0 = time.perf_counter()
        ok, mask = v.verify()
        dt = time.perf_counter() - t0
        if not ok:
            bad = [i for i, b in enumerate(mask) if not b]
            kinds = sorted({rows[i][0].type_() for i in bad})
            raise AssertionError(
                f"mixed mega-commit failed verification: {len(bad)} bad "
                f"lanes, schemes {kinds}, first {bad[:8]}")
        return dt

    run()  # warm both kernels' compiles
    detail["mixed_megacommit_ms"] = round(min(run() for _ in range(3)) * 1e3, 2)
    detail["mixed_megacommit_shape"] = f"{n_half} ed25519 + {n_half} sr25519"
    # reduced-fetch accounting: a happy window resolves from the 8-byte
    # headers; the full per-lane masks cross the tunnel only on failure
    from cometbft_tpu.ops import ed25519_kernel as _EK

    _EK.reset_fetch_stats()
    run()
    _fs = _EK.fetch_stats()
    if _fs["happy_fetches"]:
        detail["fetch_bytes_happy_path"] = (
            _fs["happy_bytes"] // _fs["happy_fetches"])
    detail["fetch_stats"] = _fs
    # decomposition: host staging (pure host work, measured directly) vs
    # device compute (rep-differenced below) vs the ~89 ms tunnel RTT the
    # synchronous mask fetch pays on this dev box. staging+device is the
    # co-located estimate — what the commit-verify costs with the chip
    # attached to the host (BASELINE's <5 ms north star assumes that).
    from cometbft_tpu.crypto import sr25519_math as srm
    from cometbft_tpu.ops import ed25519_kernel as EK
    from cometbft_tpu.ops import pallas_verify as PVsr
    from cometbft_tpu.ops import sr25519_kernel as SRK

    ed_rows = rows[:n_half]
    sr_rows = rows[n_half:]
    t0 = time.perf_counter()
    eb = EK.bucket_size(n_half)
    EK.stage_batch([p.bytes_() for p, _, _ in ed_rows],
                   [m for _, m, _ in ed_rows],
                   [s for _, _, s in ed_rows], eb)
    t_ed_stage = time.perf_counter() - t0
    t0 = time.perf_counter()
    pubs = [pk.bytes_() for pk, _, _ in sr_rows]
    msgs = [m for _, m, _ in sr_rows]
    sigs = [s for _, _, s in sr_rows]
    _, _, _, a_dev, rw, sw, kw = SRK.stage_batch_sr(pubs, msgs, sigs)
    t_sr_stage = time.perf_counter() - t0
    # rep-differencing must not re-transfer per call: pin the word arrays
    # on device once
    import jax.numpy as jnp

    rw, sw, kw = jnp.asarray(rw), jnp.asarray(sw), jnp.asarray(kw)
    detail["mixed_host_staging_ms"] = round((t_ed_stage + t_sr_stage) * 1e3, 1)
    detail["mixed_host_staging_split_ms"] = {
        "ed25519": round(t_ed_stage * 1e3, 1),
        "sr25519": round(t_sr_stage * 1e3, 1),
    }
    detail["staging_us_per_row"] = {
        "ed25519": round(t_ed_stage / n_half * 1e6, 2),
        "sr25519": round(t_sr_stage / n_half * 1e6, 2),
    }
    from cometbft_tpu.ops import hashvec as _hv

    detail["hashvec_native"] = _hv.native_available()
    detail["hashvec_rows"] = _hv.stats()
    # per-row Merlin challenge cost (native batch path), for comparison
    # with r4's 0.03 ms/row ctypes-per-op number
    t0 = time.perf_counter()
    srm.batch_compute_challenges(
        pubs[:1024], [s[:32] for s in sigs[:1024]], msgs[:1024])
    detail["mixed_host_challenge_us_per_row"] = round(
        (time.perf_counter() - t0) / 1024 * 1e6, 2)

    # sr25519 device compute, rep-differenced on the staged sub-batch via
    # the production Pallas path (falls back to the XLA ladder only if the
    # Pallas trace fails). Pair (2, 8) puts ~60 ms of device work between
    # the two timing points (r4's (1, 4) was swamped by tunnel noise and
    # recorded a negative slope); measure_device_compute refuses
    # non-positive slopes and loops until two quiet runs agree.
    use_pallas = (EK._pallas_available()
                  and rw.shape[1] % PVsr.LANES == 0
                  and not SRK._pallas_gate.broken)
    sr_fn = PVsr.verify_pallas_sr if use_pallas else SRK.verify_math_sr
    detail["sr25519_device_path"] = "pallas" if use_pallas else "xla"
    sr_best, sr_runs, sr_stats = measure_device_compute(
        sr_fn, a_dev, rw, sw, kw, rep_pair=(2, 8))
    detail["sr25519_device_compute_ms"] = round(sr_best, 2)
    detail["sr25519_device_runs_ms"] = sr_runs
    # honest spread over ALL post-warmup runs (median/p90/spread_pct) —
    # repeatability_pct IS the spread now, same stat as ed25519's
    detail["sr25519_device_repeatability_pct"] = sr_stats["spread_pct"]
    detail["sr25519_device_run_stats"] = sr_stats
    detail["sr25519_device_batch"] = rw.shape[1]
    ed_ms = detail.get("device_compute_ms_per_batch")
    if isinstance(ed_ms, (int, float)):
        # scale the 10240-lane ed number to this bench's ed sub-batch
        ed_share = ed_ms * EK.bucket_size(n_half) / EK.bucket_size(BATCH)
        detail["mixed_colocated_estimate_ms"] = round(
            detail["mixed_host_staging_ms"] + ed_share + sr_best, 1)
        detail["mixed_colocated_note"] = (
            "host staging + both schemes' rep-differenced device compute; "
            "the wall number above additionally pays the dev-box tunnel "
            "(~89 ms RTT on the mask fetch + ~45 ms/MB transfers)")


def bench_attribution(detail: dict) -> None:
    """ISSUE 6 flight recorder: arm libs/trace.py around a streaming
    verify window and record WHERE the wall time went — rolling stage
    shares (queue/stage/transfer/compute/fetch/resolve) and MEASURED
    bytes-per-sig from the spans' wire-byte counters — so the r06+
    trajectory records why a number moved, not just that it did. The
    mesh and reduced-send PRs are judged against these shares (the
    tunnel-bound claim predicts transfer+fetch dominate)."""
    from cometbft_tpu.libs import trace
    from cometbft_tpu.ops import ed25519_kernel as K

    n = min(BATCH, 4096)
    _, pubs, msgs, sigs = _mk_sigs(n, min(n, 1024))
    cache = K.PubKeyCache()
    ok, _ = K.verify_batch(pubs, msgs, sigs, cache=cache)  # warm compile
    assert ok, "attribution warm-up batch failed"
    prev_enabled = trace.enabled()
    prev_capacity = trace.capacity()
    prev_slow = trace.slow_budget_ms()
    trace.configure(enabled=True, capacity=65536, slow_ms=-1.0)
    trace.reset_attribution()
    try:
        t0 = time.perf_counter()
        thunks = [K.verify_batch_async(pubs, msgs, sigs, cache=cache)
                  for _ in range(4)]
        results = K.resolve_batches(thunks)
        wall = time.perf_counter() - t0
        assert all(m.all() for m in results)
        attr = trace.attribution()
    finally:
        if prev_enabled:
            # an operator armed the tracer (CBFT_TRACE=1) for the whole
            # bench session — re-arm with their ring size and slow budget
            # rather than disarming. Their pre-bench spans were already
            # dropped when this scenario took over the ring; skip a
            # second rebuild (which would also drop this window's spans)
            # when the ring size already matches.
            trace.configure(
                enabled=True,
                capacity=None if prev_capacity == trace.capacity()
                else prev_capacity,
                slow_ms=prev_slow)
        else:
            trace.reset()
    # coverage: the fraction of the window's wall time the stage-
    # categorized spans explain (acceptance asks >=95% on the per-batch
    # path; the remainder is Python glue between spans)
    attr["trace_coverage"] = round(
        min(1.0, attr["total_us"] / 1e6 / wall), 4)
    attr["window_wall_ms"] = round(wall * 1e3, 2)
    attr["window_rows"] = 4 * n
    attr["note"] = (
        "rolling stage shares over a 4-batch streaming window; "
        "bytes_per_sig_* are measured off span wire-byte counters "
        "(h2d staged words + pubkey tables tx, reduced-fetch headers/"
        "payloads rx), not estimated from shapes")
    # the live tunnel estimator's view of the same window lands once in
    # the artifact, as the top-level `tunnel_model` detail (main())
    detail["attribution"] = attr


def bench_challenge(detail: dict) -> None:
    """ISSUE 20 device challenge derivation: per-row cost of
    k = SHA-512(R||A||M) mod L on the host path (vectorized hashvec) vs
    the device path (plan + descriptor-stream pack + lane-parallel
    SHA-512/Barrett derive), over vote-shaped rows (shared prefix,
    8-byte variable timestamp, common chain-id trailer) — the message
    geometry the wire-bound ≤82 B/sig sentinel is judged on."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from cometbft_tpu.crypto import ed25519
    from cometbft_tpu.libs.prefixrows import PrefixedMsg
    from cometbft_tpu.ops import challenge as CH
    from cometbft_tpu.ops import ed25519_kernel as EK
    from cometbft_tpu.ops import hashvec as hv

    n = 1024
    prefix = b"bench-challenge-" + b"p" * 89  # one shared 105 B prefix
    privs = [ed25519.gen_priv_key() for _ in range(64)]
    pubs, msgs, sigs = [], [], []
    for i in range(n):
        p = privs[i % 64]
        m = PrefixedMsg(prefix,
                        secrets.token_bytes(8) + b"|bench-chain")
        pubs.append(p.pub_key().bytes_())
        msgs.append(m)
        sigs.append(p.sign(bytes(m)))
    b = EK.bucket_size(n)
    pre_ok, _safe, sig_rows, pub_rows = EK._structural_stage(pubs, sigs)

    # host path: the exact vectorized twin the kernel's fallback rungs use
    datas = [sigs[i][:32] + pubs[i] + bytes(msgs[i]) for i in range(n)]
    t0 = time.perf_counter()
    hv.sha512_mod_l_words(datas)
    host_us = (time.perf_counter() - t0) / n * 1e6

    # device path: plan + pack + derive, everything a real batch pays
    # per flush once the prefix table is resident
    CH.reset()
    plan = CH.plan_batch(msgs, pre_ok, put_key="bench")
    if plan is None:
        detail["challenge_us_per_row"] = {
            "host": round(host_us, 2), "device": None,
            "note": f"plan_batch declined: {CH.stats()}"}
        return
    block = np.zeros(CH.block_words(b, plan.var), dtype=np.uint32)
    aw = np.zeros((8, b), dtype=np.uint32)
    aw[0, :] = 1
    aw[:, :n] = np.ascontiguousarray(pub_rows).view("<u4").T
    awd = jnp.asarray(aw)
    run = CH.derive_fn(b, plan.var, plan.plen, plan.tlen, 0, False)
    EK._pack_device_block(sig_rows, b, plan, block)
    out = run(jnp.asarray(block), awd, plan.dev_tab)
    jax.block_until_ready(out)  # compile outside the timed window
    reps = 8
    t0 = time.perf_counter()
    for _ in range(reps):
        p = CH.plan_batch(msgs, pre_ok, put_key="bench")
        EK._pack_device_block(sig_rows, b, p, block)
        out = run(jnp.asarray(block), awd, p.dev_tab)
    jax.block_until_ready(out)
    dev_us = (time.perf_counter() - t0) / (reps * n) * 1e6

    detail["challenge_us_per_row"] = {
        "host": round(host_us, 2),
        "device": round(dev_us, 2),
    }
    detail["challenge"] = {
        "lanes": n,
        "lanes_device": plan.n_eligible,
        "lanes_host_fallback": plan.n_fallback,
        "geometry": {"plen": plan.plen, "tlen": plan.tlen,
                     "var": plan.var},
        "wire_block_bytes": int(block.nbytes),
        "wire_bytes_per_sig": round(block.nbytes / n, 1),
        "counters": CH.stats(),
        "note": (
            "device path includes plan + descriptor pack + lane-parallel "
            "SHA-512/Barrett derive; wire_bytes_per_sig is the flat-block "
            "cost (R/s + descriptors) the k plane no longer adds 32 B to"),
    }


def bench_light_client(detail: dict) -> None:
    """BASELINE config 4: bisection over a lazily-generated LC_HEIGHT-high
    chain with LC_VALS validators and periodic valset churn; every hop is
    two device-batched commit verifications."""
    import asyncio

    from cometbft_tpu import light
    from cometbft_tpu.crypto import ed25519
    from cometbft_tpu.light.provider import Provider
    from cometbft_tpu.light.store import LightStore
    from cometbft_tpu.store import MemDB
    from cometbft_tpu.types.basic import BlockID, PartSetHeader, SignedMsgType
    from cometbft_tpu.types.block import Header
    from cometbft_tpu.types.light import LightBlock, SignedHeader
    from cometbft_tpu.types.validator import Validator, ValidatorSet
    from cometbft_tpu.types.vote import Vote
    from cometbft_tpu.types.vote_set import VoteSet
    from cometbft_tpu.utils import cmttime

    CHURN_EVERY = max(LC_HEIGHT // 8, 1)  # 8 valset versions across the chain
    REPLACE_FRAC = 0.5  # half the set changes per version: forces pivots
    base_time = cmttime.now().seconds - LC_HEIGHT - 1000

    # pool must not wrap across the 8 valset versions, or a distant version
    # aliases the trusted one and bisection degenerates to a single jump
    pool = [ed25519.gen_priv_key() for _ in range(LC_VALS * 8)]

    class LazyChain(Provider):
        def __init__(self):
            self._valsets: dict[int, tuple] = {}
            self._blocks: dict[int, LightBlock] = {}
            self.gen_s = 0.0  # harness block-generation time (Python
            # signing of LC_VALS votes/block — NOT client work)

        def _valset(self, h):
            ver = h // CHURN_EVERY
            got = self._valsets.get(ver)
            if got is None:
                # deterministic rolling selection from the key pool
                start = (ver * int(LC_VALS * REPLACE_FRAC)) % (len(pool) - LC_VALS)
                privs = pool[start:start + LC_VALS]
                vs = ValidatorSet([Validator.new(p.pub_key(), 10) for p in privs])
                by_addr = {p.pub_key().address(): p for p in privs}
                privs = [by_addr[v.address] for v in vs.validators]
                got = (vs, privs)
                self._valsets[ver] = got
            return got

        def _block(self, h):
            lb = self._blocks.get(h)
            if lb is not None:
                return lb
            _t0 = time.perf_counter()
            lb = self._gen_block(h)
            self.gen_s += time.perf_counter() - _t0
            return lb

        def _gen_block(self, h):
            vs, privs = self._valset(h)
            nvs, _ = self._valset(h + 1)
            header = Header(
                chain_id="bench-lc", height=h,
                time=cmttime.Timestamp(base_time + h, 0),
                last_block_id=BlockID(
                    hash=b"\x07" * 32,
                    part_set_header=PartSetHeader(total=1, hash=b"\x08" * 32)),
                validators_hash=vs.hash(), next_validators_hash=nvs.hash(),
                consensus_hash=b"\x01" * 32, app_hash=b"\x02" * 32,
                last_results_hash=b"\x03" * 32, data_hash=b"\x04" * 32,
                last_commit_hash=b"\x05" * 32, evidence_hash=b"\x06" * 32,
                proposer_address=vs.validators[0].address,
            )
            bid = BlockID(hash=header.hash(),
                          part_set_header=PartSetHeader(total=1, hash=b"\x09" * 32))
            vote_set = VoteSet("bench-lc", h, 1, SignedMsgType.PRECOMMIT, vs)
            for i, p in enumerate(privs):
                v = Vote(type_=SignedMsgType.PRECOMMIT, height=h, round_=1,
                         block_id=bid, timestamp=cmttime.canonical_now_ms(),
                         validator_address=p.pub_key().address(), validator_index=i)
                v.signature = p.sign(v.sign_bytes("bench-lc"))
                vote_set.add_vote(v)
            lb = LightBlock(
                signed_header=SignedHeader(header=header, commit=vote_set.make_commit()),
                validator_set=vs)
            self._blocks[h] = lb
            return lb

        async def light_block(self, height):
            return self._block(height if height else LC_HEIGHT)

        async def report_evidence(self, ev):
            pass

    async def run():
        provider = LazyChain()
        first = provider._block(1)
        client = light.Client(
            "bench-lc",
            light.TrustOptions(
                period_ns=10**18, height=1, hash_=first.hash()),
            provider, [LazyChain()], LightStore(MemDB()),
        )
        await client.initialize()
        # decompose the hop: harness generation (provider.gen_s), device
        # prefetch (wrapped), remainder = client host work
        from cometbft_tpu.types import validation as _val

        fetch = {"s": 0.0}
        orig = _val.prefetch_staged

        def timed_prefetch(staged):
            t0 = time.perf_counter()
            try:
                return orig(staged)
            finally:
                fetch["s"] += time.perf_counter() - t0

        _val.prefetch_staged = timed_prefetch
        # the verifier imported the symbol directly — patch there too
        from cometbft_tpu.light import verifier as _verif

        _verif.prefetch_staged = timed_prefetch
        gen0 = provider.gen_s
        try:
            t0 = time.perf_counter()
            await client.verify_light_block_at_height(LC_HEIGHT)
            wall = time.perf_counter() - t0
        finally:
            _val.prefetch_staged = orig
            _verif.prefetch_staged = orig
        return wall, client.store.size(), provider.gen_s - gen0, fetch["s"]

    wall, hops, gen_s, fetch_s = asyncio.run(run())
    detail["lc_bisection_s"] = round(wall, 2)
    detail["lc_bisection_hops"] = hops
    detail["lc_client_s"] = round(wall - gen_s, 2)
    detail["lc_hop_breakdown_ms"] = {
        "harness_block_generation": round(gen_s / max(hops, 1) * 1e3, 1),
        "device_prefetch": round(fetch_s / max(hops, 1) * 1e3, 1),
        "client_host_other": round(
            (wall - gen_s - fetch_s) / max(hops, 1) * 1e3, 1),
    }
    detail["lc_shape"] = f"height {LC_HEIGHT}, {LC_VALS} validators, churn every {CHURN_EVERY}"


def bench_light_fleet(detail: dict) -> None:
    """Serving-plane scenario (light/fleet.py): FLEET_CLIENTS simulated
    concurrent light clients hit ONE LightFleet over a provider link
    degraded by the armed netchaos profile (latency+jitter+drop sampled
    from p2p/netchaos's link config — the same model the conn wrapper
    applies to real sockets). Requests follow a serving mix: most
    clients want the head, a tail bisects random history. Mid-soak the
    link suffers a full outage (the partition analog) and heals; the
    post-heal p99 is reported. Headline numbers: lc_amortized_ms
    (total wall / clients — the millions-of-users metric, enforced
    lower-is-better by the sentinel) and lc_cache_hit_rate
    (informational: a workload-mix property)."""
    import asyncio
    import random as _random

    from cometbft_tpu import light
    from cometbft_tpu.crypto import ed25519
    from cometbft_tpu.light.provider import Provider
    from cometbft_tpu.p2p import netchaos
    from cometbft_tpu.types.basic import BlockID, PartSetHeader, SignedMsgType
    from cometbft_tpu.types.block import Header
    from cometbft_tpu.types.light import LightBlock, SignedHeader
    from cometbft_tpu.types.validator import Validator, ValidatorSet
    from cometbft_tpu.types.vote import Vote
    from cometbft_tpu.types.vote_set import VoteSet
    from cometbft_tpu.utils import cmttime

    CHURN_EVERY = max(FLEET_HEIGHT // 8, 1)
    base_time = cmttime.now().seconds - FLEET_HEIGHT - 1000
    pool = [ed25519.gen_priv_key() for _ in range(FLEET_VALS * 4)]

    class LazyChain(Provider):
        def __init__(self):
            self._valsets: dict[int, tuple] = {}
            self._blocks: dict[int, LightBlock] = {}
            self.calls = 0

        def _valset(self, h):
            ver = h // CHURN_EVERY
            got = self._valsets.get(ver)
            if got is None:
                start = (ver * (FLEET_VALS // 2)) % (len(pool) - FLEET_VALS)
                privs = pool[start:start + FLEET_VALS]
                vs = ValidatorSet(
                    [Validator.new(p.pub_key(), 10) for p in privs])
                by_addr = {p.pub_key().address(): p for p in privs}
                got = (vs, [by_addr[v.address] for v in vs.validators])
                self._valsets[ver] = got
            return got

        def _block(self, h):
            lb = self._blocks.get(h)
            if lb is None:
                vs, privs = self._valset(h)
                nvs, _ = self._valset(h + 1)
                header = Header(
                    chain_id="bench-fleet", height=h,
                    time=cmttime.Timestamp(base_time + h, 0),
                    last_block_id=BlockID(
                        hash=b"\x07" * 32,
                        part_set_header=PartSetHeader(total=1, hash=b"\x08" * 32)),
                    validators_hash=vs.hash(), next_validators_hash=nvs.hash(),
                    consensus_hash=b"\x01" * 32, app_hash=b"\x02" * 32,
                    last_results_hash=b"\x03" * 32, data_hash=b"\x04" * 32,
                    last_commit_hash=b"\x05" * 32, evidence_hash=b"\x06" * 32,
                    proposer_address=vs.validators[0].address,
                )
                bid = BlockID(hash=header.hash(),
                              part_set_header=PartSetHeader(total=1,
                                                            hash=b"\x09" * 32))
                vote_set = VoteSet("bench-fleet", h, 1,
                                   SignedMsgType.PRECOMMIT, vs)
                for i, p in enumerate(privs):
                    v = Vote(type_=SignedMsgType.PRECOMMIT, height=h, round_=1,
                             block_id=bid, timestamp=cmttime.canonical_now_ms(),
                             validator_address=p.pub_key().address(),
                             validator_index=i)
                    v.signature = p.sign(v.sign_bytes("bench-fleet"))
                    vote_set.add_vote(v)
                lb = LightBlock(
                    signed_header=SignedHeader(header=header,
                                               commit=vote_set.make_commit()),
                    validator_set=vs)
                self._blocks[h] = lb
            return lb

        async def light_block(self, height):
            self.calls += 1
            return self._block(height if height else FLEET_HEIGHT)

        async def report_evidence(self, ev):
            pass

    class DegradedLink(Provider):
        """The provider behind a lossy wire: per-fetch delay and drop
        sampled from the ARMED netchaos link config (the fleet pays the
        same latency model real sockets would under ChaosConn)."""

        def __init__(self, inner):
            self.inner = inner
            self.rng = _random.Random(7)
            self.outage = False
            self.dropped = 0

        @property
        def calls(self):
            return self.inner.calls

        async def light_block(self, height):
            if self.outage:
                raise light.errors.ErrLightBlockNotFound("link outage")
            cfg = (netchaos.snapshot().get("config") or {})
            delay = cfg.get("latency", 0.0) + self.rng.uniform(
                0, cfg.get("jitter", 0.0))
            if delay:
                await asyncio.sleep(delay)
            if cfg.get("drop", 0.0) and self.rng.random() < cfg["drop"]:
                self.dropped += 1
                raise light.errors.ErrLightBlockNotFound(
                    "netchaos: fetch dropped")
            return await self.inner.light_block(height)

        async def report_evidence(self, ev):
            pass

    async def run():
        netchaos.reset()
        # armed for this scenario only: the finally below must clear it
        # even on a mid-soak failure, or every later bench section runs
        # over silently degraded in-process links
        netchaos.arm_spec("latency=0.002,jitter=0.002,drop=0.002,seed=7")
        try:
            return await _soak()
        finally:
            netchaos.reset()

    async def _soak():
        chain = LazyChain()
        link = DegradedLink(chain)
        first = chain._block(1)
        fleet = light.LightFleet(
            "bench-fleet", link,
            light.TrustOptions(period_ns=10 ** 18, height=1,
                               hash_=first.hash()),
            cache_capacity=4096, skip_base=16, trust_period_ns=10 ** 18,
            max_inflight=4096)
        await fleet.initialize()
        rng = _random.Random(11)
        # serving mix: 70% want the head, 20% a hot recent window, 10%
        # bisect random history
        heights = []
        for _ in range(FLEET_CLIENTS):
            r = rng.random()
            if r < 0.70:
                heights.append(FLEET_HEIGHT)
            elif r < 0.90:
                heights.append(FLEET_HEIGHT - rng.randint(1, 64))
            else:
                heights.append(rng.randint(FLEET_HEIGHT // 2, FLEET_HEIGHT))
        lat: list[float] = []
        errors = 0

        async def one(h):
            # a real client retries a failed request once (the degraded
            # link drops ~0.2% of fetches, and one drop mid-bisection
            # fails every coalesced waiter on that flight)
            nonlocal errors
            t0 = time.perf_counter()
            for attempt in (0, 1):
                try:
                    await fleet.verify_height(h)
                    lat.append(time.perf_counter() - t0)
                    return
                except light.LightClientError:
                    if attempt:
                        errors += 1

        # clients arrive in waves (the serving arrival process), not as
        # one synchronized burst: the first wave coalesces onto shared
        # flights, later waves hit the checkpoint cache
        wave = max(256, FLEET_CLIENTS // 20)
        t0 = time.perf_counter()
        for i in range(0, len(heights), wave):
            await asyncio.gather(*(one(h) for h in heights[i:i + wave]))
        wall = time.perf_counter() - t0

        # ---- outage + heal: the partition analog on the provider link.
        # Requests during the outage fail fast; after the heal a fresh
        # burst must recover to a serving p99
        link.outage = True
        out_err = 0
        for h in range(FLEET_HEIGHT - 200, FLEET_HEIGHT - 180):
            try:
                await fleet.verify_height(h)
            except light.LightClientError:
                out_err += 1
        link.outage = False
        heal_lat: list[float] = []
        for h in range(FLEET_HEIGHT - 200, FLEET_HEIGHT - 100):
            t1 = time.perf_counter()
            try:
                await fleet.verify_height(h)
                heal_lat.append(time.perf_counter() - t1)
            except light.LightClientError:
                pass
        return fleet, link, wall, lat, errors, out_err, heal_lat

    fleet, link, wall, lat, errors, out_err, heal_lat = asyncio.run(run())
    h = fleet.health()
    lat.sort()
    heal_lat.sort()
    detail["lc_amortized_ms"] = round(wall / max(FLEET_CLIENTS, 1) * 1e3, 3)
    detail["lc_cache_hit_rate"] = h["cache"]["hit_rate"]
    detail["fleet"] = {
        "clients": FLEET_CLIENTS,
        "wall_s": round(wall, 2),
        "requests": h["requests"],
        "cache_hits": h["cache_hits"],
        "coalesced": h["coalesced"],
        "verified": h["verified"],
        "amortization": h["amortization"],
        "errors": errors,
        "provider_fetches": link.calls,
        "fetches_dropped": link.dropped,
        "hops_per_verification": round(link.calls / h["verified"], 2)
        if h["verified"] else None,
        "p50_ms": round(lat[len(lat) // 2] * 1e3, 3) if lat else None,
        "p99_ms": round(lat[min(len(lat) - 1, int(len(lat) * 0.99))] * 1e3,
                        3) if lat else None,
        "outage_errors": out_err,
        "p99_heal_ms": round(
            heal_lat[min(len(heal_lat) - 1, int(len(heal_lat) * 0.99))]
            * 1e3, 3) if heal_lat else None,
        "shape": f"height {FLEET_HEIGHT}, {FLEET_VALS} validators, "
                 f"churn every {CHURN_EVERY}, netchaos "
                 f"latency=2ms jitter=2ms drop=0.2%",
    }


def bench_bls(detail: dict) -> None:
    """BLS12-381 scenario: aggregate-BLS vs batched-ed25519 commit
    verify at BENCH_BLS_SIZES validators (default 1k/10k/100k), with the
    crossover committee size recorded. Same-sign-bytes votes (the BLS
    commit-certificate shape: vote bytes carry no validator-specific
    field, and PoP aggregation folds identical messages), so aggregate
    cost is sig-sum + ONE pairing-product check while batched ed25519
    stays one lane-verify per validator.

    On a host without an accelerator the larger sizes are extrapolated
    from the measured linear model (aggregate = a + b*n; every O(n) term
    is cheap point adds) and marked as such — a TPU round measures all
    sizes directly. BENCH_BLS_SIZES / BENCH_BLS_MEASURE_CAP override."""
    from cometbft_tpu.crypto import fallback as O

    sizes = [int(s) for s in os.environ.get(
        "BENCH_BLS_SIZES", "1000,10000,100000").split(",")]
    import jax as _jax

    on_accel = any(d.platform != "cpu" for d in _jax.devices())
    cap = int(os.environ.get(
        "BENCH_BLS_MEASURE_CAP", "0" if on_accel else "4096"))
    _progress("bls: building incremental keys/sigs")
    d: dict = {"sizes": sizes, "aggregate_ms": {}, "batched_ed25519_ms": {},
               "distinct_messages": 1,
               "note": "same-sign-bytes votes aggregate their pubkeys "
                       "(PoP); aggregate cost = O(n) point adds + one "
                       "pairing-product check"}
    n_max = max(sizes)
    n_meas = min(n_max, cap) if cap else n_max
    msg = b"bench-bls-commit-height-12345"
    dstb = __import__(
        "cometbft_tpu.crypto.bls12381", fromlist=["DST"]).DST
    h = O.bls_hash_to_g2(msg, dstb)
    # sk_i = i + 1: pk/sig chains advance by one affine add per lane
    pubs_all, sigs_all = [], []
    pk_j = O._ec_from_affine(O.BLS_G1)
    sg_j = O._ec_from_affine(h)
    g1_j = O._ec_from_affine(O.BLS_G1)
    h_j = O._ec_from_affine(h)
    for _ in range(n_meas):
        pubs_all.append(O.bls_g1_compress(O._ec_affine(O._FpOps, pk_j)))
        sigs_all.append(O.bls_g2_compress(O._ec_affine(O._Fp2Ops, sg_j)))
        pk_j = O._ec_add(O._FpOps, pk_j, g1_j)
        sg_j = O._ec_add(O._Fp2Ops, sg_j, h_j)
    # aggregate timings: oracle path (self-contained; the device path's
    # verdict is bit-identical and its cost is recorded by BENCH rounds
    # on real hardware). KeyValidate subgroup scans are amortized per
    # validator set in the serving path, so the steady-state measurement
    # pre-validates the set once outside the timed window.
    meas = sorted({min(s, n_meas) for s in sizes})
    fit_pts = []
    for n in meas:
        _progress(f"bls: aggregate verify n={n}")
        pubs, sigs = pubs_all[:n], sigs_all[:n]
        for p in pubs:
            assert O.bls_pubkey_validate(p)  # amortized KeyValidate
        t0 = time.perf_counter()
        agg = O.bls_aggregate(sigs)
        groups = [O.bls_g1_decompress(p) for p in pubs]
        acc = None
        for aff in groups:
            acc = O._ec_add(O._FpOps, acc, O._ec_from_affine(aff))
        ok = O.bls_pairing_product_is_one(
            [(O._NEG_G1, O.bls_g2_decompress(agg)),
             (O._ec_affine(O._FpOps, acc), h)])
        dt = (time.perf_counter() - t0) * 1e3
        assert ok
        fit_pts.append((n, dt))
    # linear model over the measured points (everything is O(n) adds +
    # an O(1) pairing product)
    if len(fit_pts) >= 2:
        (n1, t1), (n2, t2) = fit_pts[0], fit_pts[-1]
        slope = (t2 - t1) / max(1, (n2 - n1))
        base = t1 - slope * n1
    else:
        slope, base = 0.0, fit_pts[0][1]
    measured_ns = {n for n, _ in fit_pts}
    for n in sizes:
        if n in measured_ns:
            d["aggregate_ms"][str(n)] = round(dict(fit_pts)[n], 1)
        else:
            d["aggregate_ms"][str(n)] = round(base + slope * n, 1)
    d["aggregate_mode"] = ("measured" if n_meas >= n_max else
                           f"measured to {n_meas}, extrapolated beyond "
                           f"(linear in n; BENCH_BLS_MEASURE_CAP)")
    # batched-ed25519 comparison: measured per-sig rate on the standard
    # batch, linear in committee size
    _progress("bls: batched ed25519 comparison")
    from cometbft_tpu.ops import ed25519_kernel as EK

    edn = min(2048, n_meas)
    _, epubs, emsgs, esigs = _mk_sigs(edn, min(edn, 256))
    EK.verify_batch(epubs, emsgs, esigs)  # warm the shape
    t0 = time.perf_counter()
    ok, _m = EK.verify_batch(epubs, emsgs, esigs)
    ed_ms = (time.perf_counter() - t0) * 1e3
    assert ok
    ed_per_sig = ed_ms / edn
    for n in sizes:
        d["batched_ed25519_ms"][str(n)] = round(ed_per_sig * n, 1)
    d["batched_ed25519_note"] = (
        f"measured {edn}-sig batch on this backend, scaled linearly")
    # crossover: aggregate = base + slope*n vs ed = ed_per_sig*n
    if ed_per_sig > slope:
        cross = base / (ed_per_sig - slope)
        d["crossover_validators"] = int(max(0, cross))
        d["crossover_note"] = (
            "committee size above which one pairing-product check beats "
            "per-lane ed25519 batch verify on this backend")
    else:
        d["crossover_validators"] = None
        d["crossover_note"] = (
            "no crossover on this backend: per-signature aggregation "
            "cost exceeds the ed25519 lane rate (expect a crossover on "
            "accelerator rounds where point adds vectorize)")
    ten_k = d["aggregate_ms"].get("10000")
    if ten_k is not None:
        d["bls_aggregate_verify_ms_10k"] = ten_k
        detail["bls_aggregate_verify_ms_10k"] = ten_k
    detail["bls"] = d


def bench_cert(detail: dict) -> None:
    """Commit-certificate scenario (cometbft_tpu/cert/): the FULL
    consumer path — decode-shaped CommitCertificate -> bitmap tally ->
    sign-bytes reconstruction -> signer-pubkey aggregation -> ONE
    pairing-product check (verify_certificate) — graded against the raw
    aggregate path (sig-sum + pairing, what bench_bls measures) and
    batched per-lane ed25519, at BENCH_CERT_SIZES validators.

    Like bench_bls, sizes above BENCH_CERT_MEASURE_CAP are extrapolated
    from the measured linear model on CPU hosts (every O(n) term is
    point adds / row reconstruction; the pairing is O(1)). Serve bytes
    are EXACT at every size — encoding needs no crypto — and make the
    transport headline: certificate bytes per commit grow one BIT per
    validator (the bitmap) vs ~sig+timestamp per validator classic."""
    from cometbft_tpu.cert import build_certificate, verify_certificate
    from cometbft_tpu.crypto import bls12381
    from cometbft_tpu.crypto import fallback as O
    from cometbft_tpu.libs.bits import BitArray
    from cometbft_tpu.types.basic import BlockID, BlockIDFlag, PartSetHeader
    from cometbft_tpu.types.commit import Commit, CommitSig
    from cometbft_tpu.types.validator import Validator, ValidatorSet
    from cometbft_tpu.utils import cmttime as _ct

    sizes = [int(s) for s in os.environ.get(
        "BENCH_CERT_SIZES", "1000,10000,100000").split(",")]
    import jax as _jax

    on_accel = any(d.platform != "cpu" for d in _jax.devices())
    cap = int(os.environ.get(
        "BENCH_CERT_MEASURE_CAP", "0" if on_accel else "2048"))
    chain_id = "bench-cert"
    height, round_ = 12345, 0
    block_id = BlockID(hash=b"\x11" * 32,
                       part_set_header=PartSetHeader(1, b"\x22" * 32))
    ts = _ct.Timestamp(1_700_000_000, 0)
    d: dict = {"sizes": sizes, "cert_verify_ms": {}, "cert_build_ms": {},
               "aggregate_ms": {}, "batched_ed25519_ms": {},
               "serve_bytes": {}, "classic_commit_bytes": {}}
    n_max = max(sizes)
    n_meas = min(n_max, cap) if cap else n_max
    # canonical precommit sign-bytes for this (chain, height, block):
    # identical for every signer (one shared timestamp), so sig_i =
    # sk_i * H(m) chains by one G2 add per lane — same incremental
    # material trick as bench_bls, but the pubkeys land in a REAL
    # ValidatorSet and the commit is a REAL Commit
    probe = Commit(height=height, round_=round_, block_id=block_id,
                   signatures=[CommitSig(block_id_flag=BlockIDFlag.COMMIT,
                                         timestamp=ts)])
    from cometbft_tpu.libs.prefixrows import as_bytes as _as_bytes
    msg = _as_bytes(probe.vote_sign_bytes_all(chain_id).rows_for([0])[0])
    h = O.bls_hash_to_g2(msg, bls12381.DST)
    _progress("cert: building incremental keys/sigs")
    pubs_all, sigs_all = [], []
    pk_j = O._ec_from_affine(O.BLS_G1)
    sg_j = O._ec_from_affine(h)
    g1_j = O._ec_from_affine(O.BLS_G1)
    h_j = O._ec_from_affine(h)
    for _ in range(n_meas):
        pubs_all.append(O.bls_g1_compress(O._ec_affine(O._FpOps, pk_j)))
        sigs_all.append(O.bls_g2_compress(O._ec_affine(O._Fp2Ops, sg_j)))
        pk_j = O._ec_add(O._FpOps, pk_j, g1_j)
        sg_j = O._ec_add(O._Fp2Ops, sg_j, h_j)
    meas = sorted({min(s, n_meas) for s in sizes})
    fit_v, fit_b, fit_a = [], [], []
    for n in meas:
        _progress(f"cert: build+verify n={n}")
        vals = ValidatorSet([
            Validator(address=i.to_bytes(20, "big"),
                      pub_key=bls12381.PubKey(pubs_all[i]), voting_power=10)
            for i in range(n)])
        commit = Commit(height=height, round_=round_, block_id=block_id,
                        signatures=[
                            CommitSig(block_id_flag=BlockIDFlag.COMMIT,
                                      timestamp=ts, signature=sigs_all[i])
                            for i in range(n)])
        t0 = time.perf_counter()
        cert = build_certificate(chain_id, vals, commit)
        tb = (time.perf_counter() - t0) * 1e3
        assert cert is not None
        t0 = time.perf_counter()
        verify_certificate(cert, chain_id, vals)  # raises on failure
        tv = (time.perf_counter() - t0) * 1e3
        # raw aggregate comparison on the same material: sig-sum +
        # summed-pubkey pairing, no certificate object in the loop
        t0 = time.perf_counter()
        agg = O.bls_aggregate(sigs_all[:n])
        acc = None
        for p in pubs_all[:n]:
            acc = O._ec_add(O._FpOps, acc,
                            O._ec_from_affine(O.bls_g1_decompress(p)))
        assert O.bls_pairing_product_is_one(
            [(O._NEG_G1, O.bls_g2_decompress(agg)),
             (O._ec_affine(O._FpOps, acc), h)])
        ta = (time.perf_counter() - t0) * 1e3
        fit_v.append((n, tv))
        fit_b.append((n, tb))
        fit_a.append((n, ta))

    def _fit(pts):
        if len(pts) >= 2:
            (n1, t1), (n2, t2) = pts[0], pts[-1]
            slope = (t2 - t1) / max(1, (n2 - n1))
            return t1 - slope * n1, slope
        return pts[0][1], 0.0

    for key, pts in (("cert_verify_ms", fit_v), ("cert_build_ms", fit_b),
                     ("aggregate_ms", fit_a)):
        base, slope = _fit(pts)
        got = dict(pts)
        for n in sizes:
            d[key][str(n)] = round(got[n] if n in got else base + slope * n, 1)
    d["mode"] = ("measured" if n_meas >= n_max else
                 f"measured to {n_meas}, extrapolated beyond (linear in n; "
                 f"BENCH_CERT_MEASURE_CAP)")
    # exact transport bytes at every size (no crypto needed to encode)
    from cometbft_tpu.cert import CommitCertificate
    for n in sizes:
        k = n - n // 3  # >2/3 signer bitmap
        ba = BitArray(n)
        for i in range(k):
            ba.set_index(i, True)
        c = CommitCertificate(
            chain_id=chain_id, height=height, round_=round_,
            block_id=block_id, valset_hash=b"\x33" * 32, n_vals=n,
            signers=ba, ts_base=ts, ts_deltas=[0] * k, agg_sig=b"\x44" * 96)
        d["serve_bytes"][str(n)] = len(c.encode())
        # classic transport: k real sigs + timestamps + flags
        classic = Commit(height=height, round_=round_, block_id=block_id,
                         signatures=[
                             CommitSig(block_id_flag=BlockIDFlag.COMMIT,
                                       timestamp=ts,
                                       validator_address=b"\x55" * 20,
                                       signature=b"\x66" * 96)
                             if i < k else CommitSig.absent()
                             for i in range(n)])
        d["classic_commit_bytes"][str(n)] = len(classic.to_proto())
    # batched-ed25519 per-lane comparison (same method as bench_bls)
    _progress("cert: batched ed25519 comparison")
    from cometbft_tpu.ops import ed25519_kernel as EK
    edn = min(2048, n_meas)
    _, epubs, emsgs, esigs = _mk_sigs(edn, min(edn, 256))
    EK.verify_batch(epubs, emsgs, esigs)  # warm the shape
    t0 = time.perf_counter()
    ok, _m = EK.verify_batch(epubs, emsgs, esigs)
    ed_per_sig = (time.perf_counter() - t0) * 1e3 / edn
    assert ok
    for n in sizes:
        d["batched_ed25519_ms"][str(n)] = round(ed_per_sig * n, 1)
    ten_k = d["cert_verify_ms"].get("10000")
    if ten_k is not None:
        d["cert_verify_ms_10k"] = ten_k
        detail["cert_verify_ms_10k"] = ten_k
    sb = d["serve_bytes"].get("10000")
    if sb is not None:
        d["serve_bytes_per_commit"] = sb
    d["note"] = ("cert verify = bitmap tally + signer-pubkey aggregation "
                 "+ ONE pairing; serve bytes grow 1 bit/validator vs "
                 "~100 B/validator classic")
    detail["cert"] = d


def bench_consensus_tpu(detail: dict) -> None:
    """VERDICT r2 item 8: the N=4 in-process net with batch_vote_verification
    flushing through the REAL device backend — per-height commit latency."""
    import asyncio

    sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "tests"))
    from net_harness import make_net

    from cometbft_tpu.consensus import timeline as cmttimeline
    from cometbft_tpu.consensus.config import test_consensus_config
    from cometbft_tpu.crypto import batch as crypto_batch

    crypto_batch.set_backend("tpu")
    # heightline armed for the run: the per-phase anatomy
    # (propose/prevote/precommit/commit/apply) of the same heights the
    # p50 below times, and the fleet propagation p99
    cmttimeline.configure(enabled=True)

    async def run():
        cfg = test_consensus_config()
        cfg.batch_vote_verification = True
        net = await make_net(4, config=cfg, chain_id="bench-consensus")
        for nd in net.nodes:
            nd.cs.timeline.node = nd.name
        heights = 10  # r4 verdict: 6 heights gave ~5 gaps, too thin a p50
        stamps = {}

        await net.start()
        try:
            last = 0
            deadline = time.monotonic() + 180
            while last < heights and time.monotonic() < deadline:
                h = min(n.block_store.height() for n in net.nodes)
                if h > last:
                    # stamp only observed transitions; a multi-height jump
                    # between polls would fabricate ~0 gaps, so record the
                    # jump at its top height only
                    stamps[h] = time.perf_counter()
                    last = h
                await asyncio.sleep(0.005)
        finally:
            await net.stop()
        docs = [{"node_id": nd.name, "heights": nd.cs.timeline.snapshot(),
                 "skew": {}} for nd in net.nodes]
        agg = cmttimeline.aggregate(docs)
        if len(stamps) < 2:
            return None, agg
        # gaps only between ADJACENT observed heights (both really seen)
        gaps = sorted(
            stamps[i + 1] - stamps[i]
            for i in stamps if i + 1 in stamps
        )
        if not gaps:
            return None, agg
        return (gaps[len(gaps) // 2], len(stamps)), agg

    try:
        out, agg = asyncio.run(run())
    finally:
        crypto_batch.set_backend("auto")
        cmttimeline.reset()
    s = agg.get("summary") or {}
    if s.get("phase_ms"):
        detail["height_phase_ms"] = s["phase_ms"]
    if s.get("phase_total_ms") is not None:
        detail["height_phase_total_ms"] = s["phase_total_ms"]
    if s.get("proposal_propagation_p99_ms") is not None:
        detail["proposal_propagation_p99_ms"] = s[
            "proposal_propagation_p99_ms"]
    if out is None:
        detail["consensus_tpu"] = "FAILED: net did not commit 2+ heights in 120s"
    else:
        p50, committed = out
        detail["consensus_tpu_height_p50_ms"] = round(p50 * 1e3, 1)
        detail["consensus_tpu_heights_committed"] = committed
        detail["consensus_tpu_note"] = (
            "4-validator in-proc net, vote flushes on the real device "
            "(each flush pays the dev-box tunnel RTT)")


def _host_mesh_env(n_devices: int) -> dict:
    """Subprocess env for an n-device CPU host mesh (the shared
    axon-stripping recipe lives in parallel/mesh.host_mesh_env)."""
    from cometbft_tpu.parallel.mesh import host_mesh_env

    env = host_mesh_env(os.environ, n_devices)
    env["BENCH_MESH_DEVICES"] = str(n_devices)
    return env


def run_mesh_bench(n_devices: int = 8, timeout: float | None = None) -> dict:
    """Run the VerifyMesh scaling scenario on an n-device host mesh in a
    child process (the one robust way to guarantee a CPU-only mesh next
    to the axon plugin) and return its record — the real-numbers
    replacement for the old MULTICHIP dryrun."""
    import subprocess

    if timeout is None:
        # a machine-cold compilation cache pays one executable
        # instantiation per (chip, ladder shape); warm reruns finish in
        # minutes
        timeout = float(os.environ.get("BENCH_MESH_TIMEOUT", "3600"))
    repo = os.path.dirname(os.path.abspath(__file__))
    proc = subprocess.run(
        [sys.executable, os.path.join(repo, "bench.py"), "--mesh-child"],
        env=_host_mesh_env(n_devices), cwd=repo,
        capture_output=True, text=True, timeout=timeout,
    )
    if proc.returncode != 0:
        raise RuntimeError(
            f"mesh bench child failed (rc={proc.returncode}):\n"
            f"stdout: {proc.stdout[-2000:]}\nstderr: {proc.stderr[-4000:]}")
    return json.loads(proc.stdout.strip().splitlines()[-1])


def mesh_child_main() -> dict:
    """The in-child mesh scenario (bench.py --mesh-child): a real
    VerifyMesh scaling curve at 1/2/4/8 devices (weak scaling: constant
    per-chip rows, so every chip compiles exactly one shard shape), a
    corrupted-lane pinpoint across shards (the old dryrun's correctness
    property, kept), and a 100k-validator mega-commit through the full
    mesh. Prints ONE JSON record line."""
    import jax

    jax.config.update("jax_compilation_cache_dir",
                      os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                   ".jax_cache"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 2)
    import numpy as np

    from cometbft_tpu.crypto import ed25519_math as oracle
    from cometbft_tpu.parallel.mesh import VerifyMesh

    devices = jax.devices()
    assert devices[0].platform == "cpu", f"mesh child must be cpu: {devices}"
    want = int(os.environ.get("BENCH_MESH_DEVICES", "8"))
    assert len(devices) >= want, f"need {want} devices, have {len(devices)}"
    devices = devices[:want]

    per_chip = int(os.environ.get("BENCH_MESH_PER_CHIP", "256"))
    mega_rows = int(os.environ.get("BENCH_MESH_MEGA", "100000"))
    reps = int(os.environ.get("BENCH_MESH_REPS", "3"))

    n_keys = 64
    rng = np.random.default_rng(1234)
    base = []
    for i in range(n_keys):
        seed = rng.bytes(32)
        msg = b"mesh-bench-" + i.to_bytes(4, "big")
        base.append((oracle.public_key_from_seed(seed), msg,
                     oracle.sign(seed, msg)))

    def make(n):
        rows = [base[i % n_keys] for i in range(n)]
        return ([r[0] for r in rows], [r[1] for r in rows],
                [r[2] for r in rows])

    detail: dict = {
        "backend": "cpu (forced host devices)",
        "devices": len(devices),
        "per_chip_rows": per_chip,
        "note": ("weak-scaling curve: per-chip rows held constant so "
                 "every chip runs one ladder-bucket shard shape; "
                 "sigs/s on forced HOST devices — the shape of the "
                 "curve, not TPU magnitude, is the tracked signal"),
    }
    curve: dict = {}
    sizes = [k for k in (1, 2, 4, 8) if k <= len(devices)]
    for k in sizes:
        vm = VerifyMesh(devices[:k], placement="spread")
        n = per_chip * k
        pubs, msgs, sigs = make(n)
        mask = vm.verify("ed25519", pubs, msgs, sigs, klass="sync")
        assert mask.all(), f"warm-up mesh batch failed at {k} devices"
        runs = []
        for _ in range(reps):
            t0 = time.perf_counter()
            mask = vm.verify("ed25519", pubs, msgs, sigs, klass="sync")
            runs.append(time.perf_counter() - t0)
            assert mask.all()
        best = min(runs)
        curve[str(k)] = {
            "rows": n, "best_s": round(best, 4),
            "runs_s": [round(r, 4) for r in runs],
            "sigs_per_s": round(n / best, 1),
        }
        detail[f"device_sigs_per_s_{k}dev"] = round(n / best, 1)
        h = vm.health()
        assert h["fallbacks"] == 0 and h["evictions"] == 0, h
    detail["curve"] = curve
    if "1" in curve and str(sizes[-1]) in curve:
        detail["scaling_x%d" % sizes[-1]] = round(
            curve[str(sizes[-1])]["sigs_per_s"] / curve["1"]["sigs_per_s"], 3)

    # correctness across shards (the dryrun's verification property): a
    # corrupted lane in the middle of the batch is pinpointed, the rest
    # stay valid
    vm = VerifyMesh(devices, placement="spread")
    n = per_chip * len(devices)
    pubs, msgs, sigs = make(n)
    bad = n // 2 + 1
    sigs = list(sigs)
    sigs[bad] = sigs[bad][:32] + sigs[(bad + 1) % n][32:]
    mask = vm.verify("ed25519", pubs, msgs, sigs, klass="sync")
    want_mask = [i != bad for i in range(n)]
    assert mask.tolist() == want_mask, "sharded mask did not pinpoint"
    detail["corrupt_lane_pinpointed"] = True

    # the 100k-validator mega-commit: one batch, whole mesh
    vm = VerifyMesh(devices, placement="spread")
    pubs, msgs, sigs = make(mega_rows)
    t0 = time.perf_counter()
    mask = vm.verify("ed25519", pubs, msgs, sigs, klass="sync")
    warm = time.perf_counter() - t0  # includes the mega-shard compile
    assert mask.all()
    t0 = time.perf_counter()
    mask = vm.verify("ed25519", pubs, msgs, sigs, klass="sync")
    wall = time.perf_counter() - t0
    assert mask.all()
    detail["mega_commit_rows"] = mega_rows
    detail["mega_commit_s"] = round(wall, 3)
    detail["mega_commit_first_s"] = round(warm, 3)
    detail["mega_commit_sigs_per_s"] = round(mega_rows / wall, 1)

    headline = detail.get(f"device_sigs_per_s_{sizes[-1]}dev", 0.0)
    record = {
        "metric": "mesh_verify_scaling",
        "value": headline,
        "unit": f"sigs/sec ({sizes[-1]}-chip forced-host mesh)",
        "vs_baseline": (round(headline / curve["1"]["sigs_per_s"], 2)
                        if curve.get("1") else None),
        "detail": detail,
    }
    print(json.dumps(record))
    return record


def bench_mesh(detail: dict) -> None:
    """Multi-chip mesh scenario (subprocess on forced host devices; the
    record also stands alone as MULTICHIP_rNN via __graft_entry__).
    BENCH_MESH=0 skips it — the child pays per-device XLA compiles on a
    cold compilation cache."""
    if os.environ.get("BENCH_MESH", "1") == "0":
        detail["mesh"] = "skipped: BENCH_MESH=0"
        return
    record = run_mesh_bench(int(os.environ.get("BENCH_MESH_DEVICES", "8")))
    detail["mesh"] = record["detail"]


def bench_fleet(detail: dict) -> None:
    """Fleet-size curves over REAL OS-process testnets (ISSUE 12): for
    each size in BENCH_FLEET_SIZES (default "4,16"; the acceptance curve
    adds 50), boot a regional topology with WAN cross-region links, soak,
    and report per size:

      heights_per_s                    committed heights per wall second
      wire_bytes_per_height_per_node   p2p send bytes per height per node
      gossip_votes_per_vote_needed     vote amplification (lower = the
                                       reconciliation plane is working)
      partition_heal_p99_ms            worst partition-heal latency over
                                       BENCH_FLEET_HEAL_CYCLES cycles

    The largest size's amplification + heal numbers are lifted to the
    record top level under the sentinel's names. Env knobs:
    BENCH_FLEET=0 skips, BENCH_FLEET_SIZES, BENCH_FLEET_SOAK_S,
    BENCH_FLEET_HEAL_CYCLES, BENCH_FLEET_BASE_PORT."""
    if os.environ.get("BENCH_FLEET", "1") == "0":
        detail["fleet"] = "skipped: BENCH_FLEET=0"
        return
    import tempfile
    import urllib.parse

    from cometbft_tpu.e2e import runner as R
    from cometbft_tpu.e2e.generator import generate_fleet_manifest

    sizes = [int(s) for s in
             os.environ.get("BENCH_FLEET_SIZES", "4,16").split(",")
             if s.strip()]
    heal_cycles = int(os.environ.get("BENCH_FLEET_HEAL_CYCLES", "2"))
    soak_s = float(os.environ.get("BENCH_FLEET_SOAK_S", "12"))
    # port spans must stay BELOW the kernel ephemeral range (the guard
    # enforces it for big sizes; this container's range starts at
    # 16000): stride 2100 covers the p2p/rpc/abci port strides
    base_port = int(os.environ.get("BENCH_FLEET_BASE_PORT", "8000"))
    curve: dict = {}
    for n in sizes:
        R._resource_guard(n, base_port)
        regions = 2 if n < 8 else 4
        m = generate_fleet_manifest(n, topology="regional", regions=regions,
                                    link_profile="wan",
                                    name=f"bench-fleet-{n}")
        d = tempfile.mkdtemp(prefix=f"bench-fleet-{n}-")
        net = R.setup(m, d, base_port)
        base_port += 2100
        names = sorted(m.nodes)
        row: dict = {}
        try:
            net.app_procs = [None] * n
            R._boot_staggered(net)
            R._wait(lambda: all(R._height(net, i) >= 3 for i in range(n)),
                    150 + 4 * n, f"{n}-node bench fleet booting")

            def _tele():
                return [R._rpc(net, i, "net_telemetry", timeout=10.0)
                        .get("result", {}) for i in range(n)]

            _progress(f"fleet {n}: soaking {soak_s:.0f}s")
            h0 = max(R._height(net, i) for i in range(n))
            tele0 = _tele()
            t0 = time.perf_counter()
            time.sleep(soak_s)
            h1 = max(R._height(net, i) for i in range(n))
            dt = time.perf_counter() - t0
            tele1 = _tele()
            dh = max(1, h1 - h0)
            send = (sum(t.get("totals", {}).get("send_bytes", 0)
                        for t in tele1)
                    - sum(t.get("totals", {}).get("send_bytes", 0)
                          for t in tele0))
            row["heights_per_s"] = round((h1 - h0) / dt, 3)
            row["wire_bytes_per_height_per_node"] = round(send / dh / n, 1)
            g: dict = {}
            for t in tele1:
                for k, v in ((t.get("gossip") or {})
                             .get("totals") or {}).items():
                    g[k] = g.get(k, 0) + v
            needed = g.get("votes_recv_needed", 0)
            row["gossip_votes_per_vote_needed"] = (
                round(g.get("votes_recv", 0) / needed, 3) if needed
                else None)
            row["gossip_totals"] = g

            # partition/heal cycles: region 0 vs. the rest
            _progress(f"fleet {n}: {heal_cycles} partition-heal cycles")
            ids = R._node_ids(net)
            regs = [m.nodes[nm].region for nm in names]
            cut = [i for i in range(n) if regs[i] == 0]
            spec = ("partition=" + ".".join(ids[i] for i in cut) + "|"
                    + ".".join(ids[i] for i in range(n) if regs[i] != 0))
            arg = urllib.parse.quote(f'"{spec}"')
            heals = []

            def _heal_gauges():
                return [R._metric_value(
                    R._metrics_text(net, j),
                    "cometbft_p2p_partition_heal_seconds")
                    for j in range(n)]

            for _ in range(heal_cycles):
                # the heal gauge PERSISTS per node across cycles, so each
                # cycle's sample is the max over gauges that CHANGED from
                # their pre-cycle value — never a stale max from an
                # earlier cycle
                pre = _heal_gauges()
                for j in range(n):
                    R._rpc(net, j, f"unsafe_net_chaos?spec={arg}",
                           timeout=10.0)
                time.sleep(2.0)
                hq = max(R._height(net, i) for i in range(n))
                for j in range(n):
                    R._rpc(net, j, "unsafe_net_chaos?heal=true",
                           timeout=10.0)
                R._wait(lambda: min(R._height(net, i) for i in range(n))
                        >= hq + 1, 120 + 2 * n, "post-heal catch-up")
                post = _heal_gauges()
                changed = [v for v, p in zip(post, pre) if v != p]
                if changed:
                    heals.append(round(max(changed) * 1e3, 1))
            heals.sort()
            row["heal_samples_ms"] = heals
            row["partition_heal_p99_ms"] = heals[-1] if heals else None
        finally:
            for p in net.node_procs:
                R._kill(p)
        curve[str(n)] = row
    detail["fleet"] = {"sizes": sizes, "curve": curve}
    big = str(max(sizes))
    # sentinel names (tools/bench_compare.py): amplification is ENFORCED
    # lower-is-better; the fleet rate + heal latency stay informational
    # until a quiet round establishes their variance
    detail["gossip_votes_per_vote_needed"] = \
        curve[big].get("gossip_votes_per_vote_needed")
    detail["partition_heal_p99_ms"] = curve[big].get("partition_heal_p99_ms")
    if "50" in curve:
        detail["fleet_heights_per_s_50node"] = curve["50"]["heights_per_s"]


def bench_discovery(detail: dict) -> None:
    """Discovery-plane scenario (peer-discovery resilience PR):

      bootstrap_convergence_s     wall seconds for an ORGANIC fleet
                                  (BENCH_DISCOVERY_NODES nodes, one seed,
                                  empty address books, NO persistent
                                  wiring) to go from process spawn to
                                  every node committing — discovery IS
                                  the critical path, so this clocks the
                                  PEX plane end to end
      eclipse_book_occupancy_pct  worst per-/16-source-group share of the
                                  NEW set after a 32-identity sybil flood
                                  through the real book-intake path;
                                  the hashed-bucket geometry bounds it at
                                  stats()["src_group_occupancy_bound_pct"]

    Env knobs: BENCH_DISCOVERY=0 skips, BENCH_DISCOVERY_NODES,
    BENCH_DISCOVERY_BASE_PORT."""
    if os.environ.get("BENCH_DISCOVERY", "1") == "0":
        detail["discovery"] = "skipped: BENCH_DISCOVERY=0"
        return
    import tempfile

    from cometbft_tpu.e2e import runner as R
    from cometbft_tpu.e2e.generator import generate_fleet_manifest
    from cometbft_tpu.p2p.pex import AddrBook
    from cometbft_tpu.p2p.pex.byzantine import ByzantinePexHarness

    n = int(os.environ.get("BENCH_DISCOVERY_NODES", "6"))
    base_port = int(os.environ.get("BENCH_DISCOVERY_BASE_PORT", "8000"))
    R._resource_guard(n, base_port)
    m = generate_fleet_manifest(n, topology="organic", regions=1,
                                name=f"bench-discovery-{n}")
    d = tempfile.mkdtemp(prefix=f"bench-discovery-{n}-")
    net = R.setup(m, d, base_port)
    _progress(f"discovery: booting {n}-node organic fleet (one seed)")
    books: dict = {}
    try:
        net.app_procs = [None] * n
        t0 = time.perf_counter()
        R._boot_staggered(net)
        R._wait(lambda: all(R._height(net, i) >= m.initial_height + 2
                            for i in range(n)),
                150 + 4 * n, f"{n}-node organic fleet converging via PEX")
        boot_s = time.perf_counter() - t0
        for i in range(n):
            doc = R._rpc(net, i, "net_telemetry", timeout=10.0)
            disc = doc.get("result", {}).get("discovery") or {}
            books[f"node{i:03d}"] = disc.get("size", 0)
    finally:
        for p in net.node_procs:
            R._kill(p)

    # eclipse occupancy: the socket-free flood through the SAME intake
    # path the wire uses (32 identities, one /16, diverse forged claims)
    book = AddrBook(our_id="bench")
    ledger = ByzantinePexHarness.flood_book(book, n_identities=32,
                                            claims_per_identity=128)
    s = book.stats()
    detail["discovery"] = {
        "organic_nodes": n,
        "bootstrap_convergence_s": round(boot_s, 2),
        "addrbook_sizes": books,
        "eclipse_flood": ledger,
        "eclipse_book_occupancy_pct": s["max_src_group_occupancy_pct"],
        "eclipse_occupancy_bound_pct": s["src_group_occupancy_bound_pct"],
    }
    # sentinel names (tools/bench_compare.py)
    detail["bootstrap_convergence_s"] = round(boot_s, 2)
    detail["eclipse_book_occupancy_pct"] = s["max_src_group_occupancy_pct"]


def bench_storage(detail: dict) -> None:
    """Storage-plane scenario: consensus-WAL fsync latency (the disk
    floor under every committed height — the write_sync path EndHeight
    rides) and sqlite transactional write latency, measured on a fresh
    temp dir. Emits wal_fsync_p99_ms (TRACKED lower in
    tools/bench_compare.py) bare and under detail["storage"]."""
    import shutil
    import tempfile

    from cometbft_tpu.consensus.wal import WAL, EndHeightMessage
    from cometbft_tpu.store.db import SQLiteDB

    n = int(os.environ.get("BENCH_STORAGE_OPS", "300"))
    d = tempfile.mkdtemp(prefix="bench-storage-")
    try:
        wal = WAL(os.path.join(d, "wal", "wal.bin"))
        lat = []
        for h in range(1, n + 1):
            t0 = time.perf_counter()
            wal.write_sync(EndHeightMessage(h))
            lat.append(time.perf_counter() - t0)
        wal.close()
        lat.sort()
        p50 = lat[len(lat) // 2] * 1e3
        p99 = lat[min(len(lat) - 1, int(len(lat) * 0.99))] * 1e3

        db = SQLiteDB(os.path.join(d, "kv.db"))
        dlat = []
        payload = b"\x5a" * 512
        for i in range(n):
            t0 = time.perf_counter()
            db.set(b"bench-%06d" % i, payload)
            dlat.append(time.perf_counter() - t0)
        db.close()
        dlat.sort()
        detail["wal_fsync_p99_ms"] = round(p99, 3)
        detail["storage"] = {
            "wal_fsync_p50_ms": round(p50, 3),
            "wal_fsync_p99_ms": round(p99, 3),
            "db_write_p50_ms": round(dlat[len(dlat) // 2] * 1e3, 3),
            "db_write_p99_ms": round(
                dlat[min(len(dlat) - 1, int(len(dlat) * 0.99))] * 1e3, 3),
            "ops": n,
            "note": ("fsync latency on the bench host's disk; wide "
                     "sentinel threshold — the contract is that the WAL "
                     "write path stays one write+fsync, not the disk"),
        }
    finally:
        shutil.rmtree(d, ignore_errors=True)


def bench_scheduler(detail: dict) -> None:
    """Global verify scheduler under a mixed offered load (ISSUE 4
    acceptance): a 4-validator in-process net committing with batched
    vote verification (consensus class) while mempool-admission
    signature rows pump concurrently (mempool class, deadline-flushed or
    riding consensus flushes as filler) and blocksync-shaped commit
    windows verify (sync class). Reports:

      sched_fill_ratio_mean       rows/lanes over every dispatched batch
      sched_fragmented_fill_mean  the SAME groups dispatched one-batch-
                                  per-producer (the pre-scheduler
                                  architecture), measured on this load
      sched_latency_per_class     submit->dispatch p50/p99 ms
      sched_direct_flush_*        consensus flush-sized batches through
                                  the scheduler (with filler queued) vs
                                  the direct fragmented verifier path —
                                  the no-regression check for consensus
                                  flush latency
    """
    import asyncio

    sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "tests"))
    from light_harness import LightChain
    from net_harness import make_net

    from cometbft_tpu import sched
    from cometbft_tpu.consensus.config import test_consensus_config
    from cometbft_tpu.crypto import batch as crypto_batch
    from cometbft_tpu.crypto import ed25519
    from cometbft_tpu.types import validation

    sched.reset()
    sched.configure(enabled=True)
    out: dict = {}

    # ---- live mixed load: 4-val net + mempool pump + sync windows
    chain = LightChain("bench-sched", 12, n_vals=32)
    svals = chain.valsets[1]

    def _mempool_rows(n):
        rows = []
        for i in range(n):
            p = ed25519.gen_priv_key()
            m = b"bench-sched-tx-%d" % i
            rows.append((p.pub_key(), m, p.sign(m)))
        return rows

    pump_rows = _mempool_rows(8)

    async def run_net():
        cfg = test_consensus_config()
        cfg.batch_vote_verification = True
        net = await make_net(4, config=cfg, chain_id="bench-sched-net")
        submitted = rejected = 0
        await net.start()
        try:
            deadline = time.monotonic() + 60
            height_goal = 8
            sync_h = 1

            async def pump():
                # one row per submit — the real admission shape: every
                # check_tx stages a single signature row, which pre-PR
                # would have been its own (8-lane-padded) device batch
                nonlocal submitted, rejected
                while time.monotonic() < deadline:
                    for row in pump_rows:
                        try:
                            sched.get().submit([row], klass=sched.MEMPOOL)
                            submitted += 1
                        except sched.SchedulerSaturated:
                            rejected += 1
                    await asyncio.sleep(0.004)
                    if min(n.block_store.height() for n in net.nodes) >= height_goal:
                        return

            async def sync_windows():
                nonlocal sync_h
                while time.monotonic() < deadline:
                    staged = []
                    for h in range(sync_h, min(sync_h + 3, 12)):
                        lb = chain.blocks[h]
                        staged.append(validation.stage_verify_commit(
                            "bench-sched", svals, lb.commit.block_id, h,
                            lb.commit))
                    sync_h = sync_h + 3 if sync_h + 3 < 12 else 1
                    await asyncio.get_running_loop().run_in_executor(
                        None, validation.prefetch_staged, staged, "sync")
                    for s in staged:
                        s.finish()
                    await asyncio.sleep(0.02)
                    if min(n.block_store.height() for n in net.nodes) >= height_goal:
                        return

            tasks = [asyncio.create_task(pump()),
                     asyncio.create_task(sync_windows())]
            while time.monotonic() < deadline:
                if min(n.block_store.height() for n in net.nodes) >= height_goal:
                    break
                await asyncio.sleep(0.01)
            for t in tasks:
                await t
        finally:
            await net.stop()
        return (min(n.block_store.height() for n in net.nodes),
                submitted, rejected)

    height, submitted, rejected = asyncio.run(run_net())
    sched.get().flush()
    snap = sched.get().health()
    out["net_height"] = height
    out["mempool_rows_offered"] = submitted
    out["mempool_rows_rejected_backpressure"] = rejected
    out["fill_ratio_mean"] = snap["fill_ratio_mean"]
    out["fragmented_fill_ratio_mean"] = snap["fragmented_fill_ratio_mean"]
    out["fill_gain"] = (
        round(snap["fill_ratio_mean"] / snap["fragmented_fill_ratio_mean"], 3)
        if snap["fragmented_fill_ratio_mean"] else None)
    out["batches"] = snap["batches"]
    out["rows_total"] = snap["rows_total"]
    out["class_rows"] = snap["class_rows"]
    out["deadline_misses"] = snap["deadline_misses"]
    out["dispatch_shapes"] = snap["dispatch_shapes"]
    out["latency_per_class"] = sched.get().latency_quantiles()

    # ---- direct-flush no-regression check: flush-sized (128-row)
    # consensus batches through the scheduler (mempool filler queued)
    # vs the pre-scheduler fragmented verifier on identical rows
    privs = [ed25519.gen_priv_key() for _ in range(128)]
    rows = []
    for i, p in enumerate(privs):
        m = b"bench-flush-%d" % i
        rows.append((p.pub_key(), m, p.sign(m)))

    def p50p99(ts):
        ts = sorted(ts)
        return (round(ts[len(ts) // 2] * 1e3, 3),
                round(ts[min(len(ts) - 1, int(len(ts) * 0.99))] * 1e3, 3))

    sched_ts = []
    for _ in range(20):
        for row in pump_rows:
            try:
                sched.get().submit([row], klass=sched.MEMPOOL)
            except sched.SchedulerSaturated:
                pass
        t0 = time.perf_counter()
        mask = sched.get().verify_now(rows, sched.CONSENSUS)
        sched_ts.append(time.perf_counter() - t0)
        assert all(mask)
    direct_ts = []
    sched.configure(enabled=False)
    try:
        for _ in range(20):
            bv = crypto_batch.create_mixed_batch_verifier()
            for pk, m, s in rows:
                bv.add(pk, m, s)
            t0 = time.perf_counter()
            ok, _ = bv.verify()
            direct_ts.append(time.perf_counter() - t0)
            assert ok
    finally:
        sched.configure(enabled=True)
    out["direct_flush_sched_p50_ms"], out["direct_flush_sched_p99_ms"] = p50p99(sched_ts)
    out["direct_flush_frag_p50_ms"], out["direct_flush_frag_p99_ms"] = p50p99(direct_ts)
    out["note"] = (
        "fill_ratio_mean vs fragmented_fill_ratio_mean measures the SAME "
        "live load batched by the scheduler vs one-batch-per-producer; "
        "direct_flush_* is the consensus-flush latency no-regression pair "
        "(scheduler with filler vs pre-scheduler fragmented verifier)")
    detail["sched"] = out


def bench_soak(detail: dict) -> None:
    """Sustained-saturation soak (the overload plane's acceptance
    scenario): a 4-validator in-process net commits heights while the
    loadtime saturation generator drives admission waves well past the
    mempool ceiling. The chain must keep committing with bounded height
    latency while the mempool plane sheds — graded liveness under
    overload. Emits:

      soak_heights_per_s        committed heights/s under sustained load
      admission_txs_per_s       accepted (admitted) txs/s while shedding
      height_p99_under_load_ms  p99 inter-height gap under load (TRACKED
                                lower in tools/bench_compare.py)

    plus the per-plane shed counts, the unloaded-baseline p99, and the
    scheduler's per-class deadline-miss attribution (consensus must
    read zero)."""
    import asyncio

    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "tests"))
    from net_harness import make_net

    from cometbft_tpu import loadtime, sched
    from cometbft_tpu.consensus.config import test_consensus_config
    from cometbft_tpu.libs.overload import OverloadRegistry
    from cometbft_tpu.mempool.mempool import ErrMempoolIsFull

    sched.reset()
    sched.configure(enabled=True)
    heights_goal = int(os.environ.get("BENCH_SOAK_HEIGHTS", "30"))
    quiet_goal = int(os.environ.get("BENCH_SOAK_QUIET_HEIGHTS", "8"))
    pool_size = int(os.environ.get("BENCH_SOAK_POOL", "512"))
    inflight = int(os.environ.get("BENCH_SOAK_INFLIGHT", "64"))

    async def collect_heights(node, n: int, timeout: float) -> list[float]:
        """Stamp the next n committed heights on node's store."""
        stamps: list[float] = []
        last = node.block_store.height()
        deadline = time.monotonic() + timeout
        while len(stamps) < n and time.monotonic() < deadline:
            h = node.block_store.height()
            if h > last:
                stamps.extend(time.monotonic() for _ in range(h - last))
                last = h
            await asyncio.sleep(0.005)
        return stamps

    def p99_gap_ms(stamps: list[float]) -> float:
        gaps = sorted(b - a for a, b in zip(stamps, stamps[1:]))
        if not gaps:
            return 0.0
        return round(gaps[min(len(gaps) - 1, int(len(gaps) * 0.99))] * 1e3, 2)

    async def run() -> dict:
        cfg = test_consensus_config()
        cfg.batch_vote_verification = True  # consensus flushes ride the sched
        net = await make_net(4, config=cfg, chain_id="bench-soak-net")
        node = net.nodes[0]
        # a small pool makes saturation reachable without millions of txs;
        # the watermark dynamics are ratio-based so nothing else changes
        node.mempool.config.size = pool_size
        reg = OverloadRegistry()
        node.mempool.attach_overload(reg)
        reg.register("sched", lambda: (
            sum(sched.get()._depth.values())
            / max(1, sched.get().queue_limit)))
        await net.start()
        try:
            quiet = await collect_heights(node, quiet_goal, 60.0)

            async def submit(tx: bytes) -> bool:
                try:
                    res = await node.mempool.check_tx(tx)
                    return res.is_ok()
                except ErrMempoolIsFull:
                    return False
                except Exception:  # noqa: BLE001 - cache dupes etc.
                    return False

            totals = loadtime.LoadResult()
            stop = asyncio.Event()

            async def pump() -> None:
                # each cycle offers 4*pool_size txs — ≥2x the admission
                # ceiling even if every commit fully drains the pool.
                # max_inflight mirrors the RPC write budget: calling
                # check_tx directly bypasses the server's in-flight
                # guard, and an unbounded task wave starves the in-proc
                # validators' consensus coroutines (they share this
                # event loop — a flood the RPC guard sheds in production)
                while not stop.is_set():
                    _, res = await loadtime.generate_saturation(
                        submit, waves=4, wave_size=pool_size,
                        size=192, interval=0.005, max_inflight=inflight)
                    totals.sent += res.sent
                    totals.accepted += res.accepted
                    totals.rejected += res.rejected
                    totals.errors += res.errors

            t0 = time.monotonic()
            ptask = asyncio.create_task(pump())
            loaded = await collect_heights(node, heights_goal, 300.0)
            stop.set()
            await ptask
            elapsed = time.monotonic() - t0
        finally:
            await net.stop()
        snap = sched.get().health()
        return {
            "heights_under_load": len(loaded),
            "elapsed_s": round(elapsed, 2),
            "soak_heights_per_s": round(len(loaded) / elapsed, 2),
            "admission_txs_per_s": round(totals.accepted / elapsed, 1),
            "height_p99_unloaded_ms": p99_gap_ms(quiet),
            "height_p99_under_load_ms": p99_gap_ms(loaded),
            "offered": totals.sent,
            "accepted": totals.accepted,
            "rejected": totals.rejected,
            "errors": totals.errors,
            "sheds": {p: reg.sheds(p) for p in reg.planes()},
            "overload": reg.health(),
            "deadline_miss_by_class": snap.get("deadline_miss_by_class", {}),
            "note": ("the chain must keep committing while the mempool "
                     "plane sheds: rejected > 0 proves saturation was "
                     "reached, deadline_miss_by_class['consensus'] == 0 "
                     "proves consensus flushes never degraded"),
        }

    out = asyncio.run(run())
    detail["soak_heights_per_s"] = out["soak_heights_per_s"]
    detail["admission_txs_per_s"] = out["admission_txs_per_s"]
    detail["height_p99_under_load_ms"] = out["height_p99_under_load_ms"]
    detail["soak"] = out


def main() -> dict:
    import jax

    jax.config.update("jax_compilation_cache_dir", os.path.join(os.path.dirname(__file__), ".jax_cache"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 2)

    import jax.numpy as jnp

    from cometbft_tpu.crypto import ed25519
    from cometbft_tpu.ops import ed25519_kernel as K

    detail: dict = {"backend": jax.devices()[0].platform, "batch": BATCH}

    # -- build the batch: one "validator set" signing distinct messages
    _progress("building batch")
    privs, pubs, msgs, sigs = _mk_sigs(BATCH, min(BATCH, 10240))

    cache = K.PubKeyCache()
    _progress("warm-up compile")
    ok, _ = K.verify_batch(pubs, msgs, sigs, cache=cache)  # warm-up compile
    assert ok, "warm-up batch failed verification"

    _progress("p50 latency")
    # -- p50 synchronous single-batch latency
    lat = []
    for _ in range(ITERS):
        t0 = time.perf_counter()
        ok, mask = K.verify_batch(pubs, msgs, sigs, cache=cache)
        lat.append(time.perf_counter() - t0)
        assert ok
    detail["p50_batch_latency_ms"] = round(sorted(lat)[len(lat) // 2] * 1e3, 2)
    detail["tunnel_note"] = "single-batch latency includes ~89ms axon-tunnel RTT floor"

    # -- kernel-only device compute (rep-differencing), run TWICE: the
    # device-bound co-headline must be repeatable to be comparable across
    # rounds (the stream number below is tunnel-bound and collapses under
    # dev-box contention; this one must not).
    b = K.bucket_size(BATCH)
    _, safe_pubs, rw, sw, kw = K.stage_batch(pubs, msgs, sigs, b)
    _, a_dev = cache.stage(safe_pubs, b)
    device_sigs_per_s = None
    _progress("device compute rep-differencing")
    try:
        from cometbft_tpu.ops import pallas_verify as PV

        ed_fn = PV.verify_pallas if K._pallas_available() else K.verify_math
        args = (jnp.asarray(rw), jnp.asarray(sw), jnp.asarray(kw))
        best, runs, stats = measure_device_compute(ed_fn, a_dev, *args)
        detail["device_compute_ms_per_batch"] = round(best, 2)
        detail["device_compute_runs_ms"] = runs
        # same honest-spread stat as sr25519 (median/p90/spread over all
        # post-warmup runs; min-vs-min agreement only as converged flag)
        detail["device_repeatability_pct"] = stats["spread_pct"]
        detail["device_compute_run_stats"] = stats
        device_sigs_per_s = BATCH / (best / 1e3)
        detail["device_sigs_per_s"] = round(device_sigs_per_s, 1)
        # Roofline statement (VERDICT r4 weak-9): the verify program
        # executes 2,815 field mul+sq per 128-lane block — 51-window
        # double-scalar ladder (50 scanned window steps at 30M+20S) +
        # 17-entry table build (112M+32S) + R decompression and identity
        # check (exact counts: traced op census over the scan body and
        # surrounding program). At the microbench-measured ~40 ns per
        # 128-lane field mul (pre-rolled conv 15 ns + interval-checker-
        # proved-minimal carry/fold rounds) the multiply floor is 9.0 ms
        # per 10,240 sigs; add/sub chains (~2,639 ops/block) add ~2 ms.
        # Quiet-box measurements sit AT this floor (r4 best 9.8 ms), so
        # the kernel is VPU-arithmetic-bound: the <5 ms north star needs
        # a cheaper field mul, and the conv core already runs at the ~4
        # vreg-ops/cycle issue limit. Recorded dead ends: Karatsuba,
        # cross-lane MSM, int16 tables, stacked-coordinate conv.
        detail["kernel_roofline"] = {
            "mul_sq_per_128_lanes": 2815,
            "addsub_per_128_lanes": 2639,
            "ns_per_mul_measured": 40,
            "mul_floor_ms_per_10240": 9.0,
            "floor_with_addsub_ms": 11.1,
            "floor_note": "floor uses the contention-inclusive 40 ns/mul "
                          "microbench rate; quiet-tunnel batch measurements "
                          "as low as ~7.5 ms imply the true amortized rate "
                          "is ~30-35 ns/mul — the program sits at its "
                          "arithmetic bound either way",
            "bound": "VPU arithmetic (field-mul issue rate); conv core at "
                     "~4 vreg-ops/cycle — <5 ms requires a cheaper mul, "
                     "not more tuning of this program",
        }
    except Exception as e:  # noqa: BLE001 - CPU backend has no pallas path
        detail["device_compute_ms_per_batch"] = f"skipped: {e}"

    # -- vote-flush device latency (VERDICT r3 weak item 5): the consensus
    # hot path flushes ~100-200 vote signatures per round; this is the
    # rep-differenced device time for one flush-sized batch — the
    # non-tunnel cost of a vote-path flush
    try:
        from cometbft_tpu.ops import pallas_verify as PV

        ed_fn = PV.verify_pallas if K._pallas_available() else K.verify_math
        fb = K.bucket_size(128)
        _, fp, frw, fsw, fkw = K.stage_batch(pubs[:128], msgs[:128], sigs[:128], fb)
        _, fa_dev = cache.stage(fp, fb)
        fl_best, _, _ = measure_device_compute(
            ed_fn, fa_dev, jnp.asarray(frw), jnp.asarray(fsw),
            jnp.asarray(fkw), rep_pair=(8, 64))
        detail["vote_flush_device_ms"] = round(fl_best, 3)
    except Exception as e:  # noqa: BLE001
        detail["vote_flush_device_ms"] = f"skipped: {e}"

    _progress("streaming throughput")
    # -- streaming throughput (wire-bound; tunnel-capped on this dev box).
    # Send-path accounting resets here so the stream window measures the
    # STEADY-STATE wire cost per signature (the validator table is warm
    # after the batches above) — the reduced-send protocol's headline.
    from cometbft_tpu.ops import residency as _residency

    _residency.reset_send_stats()
    t0 = time.perf_counter()
    thunks = [
        K.verify_batch_async(pubs, msgs, sigs, cache=cache)
        for _ in range(STREAM_BATCHES)
    ]
    results = K.resolve_batches(thunks)
    t_stream = time.perf_counter() - t0
    assert all(m.all() for m in results)
    tpu_sigs_per_s = STREAM_BATCHES * BATCH / t_stream
    detail["stream_batches"] = STREAM_BATCHES
    detail["stream_sigs_per_s"] = round(tpu_sigs_per_s, 1)
    wire = _residency.send_stats()
    detail["wire"] = wire
    detail["wire_bytes_per_sig"] = (
        wire["steady_state_bytes_per_sig"] or wire["full_path_bytes_per_sig"])

    _progress("cpu baselines")
    # -- CPU baselines: best-of-3 trials, so dev-box contention lowers the
    # baseline (and inflates the ratio) as little as possible — the
    # comparison must not get easier when the box is busy
    pk_objs = [ed25519.PubKey(pubs[i]) for i in range(CPU_SAMPLE)]
    cpu_serial = 0.0
    for _ in range(3):
        t0 = time.perf_counter()
        for i in range(CPU_SAMPLE):
            assert pk_objs[i].verify_signature(msgs[i], sigs[i])
        cpu_serial = max(cpu_serial, CPU_SAMPLE / (time.perf_counter() - t0))
    cpu_batch_pinned = cpu_serial * PINNED_VOI_BATCH_FACTOR
    detail["cpu_serial_sigs_per_s"] = round(cpu_serial, 1)
    detail["cpu_batch_pinned_sigs_per_s"] = round(cpu_batch_pinned, 1)
    detail["vs_serial"] = round(tpu_sigs_per_s / cpu_serial, 2)
    detail["vs_batch_pinned"] = round(tpu_sigs_per_s / cpu_batch_pinned, 2)
    detail["vs_batch_note"] = VS_BATCH_NOTE
    if device_sigs_per_s is not None:
        detail["device_vs_batch_pinned"] = round(
            device_sigs_per_s / cpu_batch_pinned, 2)
    # live tunnel model (libs/linkmodel.py): the streaming window above
    # fed the estimator with every measured h2d/fetch transfer, so the
    # tunnel cap is now MEASURED per run instead of the hand-measured
    # "~22 MB/s, ~89 ms" constants baked into earlier rounds' notes
    from cometbft_tpu.libs import linkmodel

    tun = linkmodel.tunnel()
    detail["tunnel_model"] = tun.snapshot()
    bw, rtt = tun.bandwidth_bps(), tun.rtt_seconds()
    if rtt > 0:
        detail["tunnel_note"] = (
            f"single-batch latency includes the measured ~{rtt * 1e3:.0f} "
            f"ms tunnel RTT floor (live estimate)")
    bps = detail.get("wire_bytes_per_sig") or 96.0
    if tun.converged() and bw > 0:
        detail["tunnel_cap_sigs_per_s"] = round(bw / bps, 1)
        detail["tunnel_cap_note"] = (
            f"stream headline is wire-bound: measured {bps:.0f} B/sig "
            f"(reduced-send accounting, was 96 pre-r06) over a measured "
            f"~{bw / 1e6:.1f} MB/s, ~{rtt * 1e3:.0f} ms RTT link (live "
            f"EWMA estimate, libs/linkmodel.py) caps it near "
            f"~{bw / bps / 1e3:.0f}k sigs/s regardless of kernel speed; "
            f"device_sigs_per_s is the chip-bound co-headline")
    else:
        detail["tunnel_cap_note"] = (
            f"stream headline is wire-bound (tunnel estimator did not "
            f"converge this run; historical dev-box figures ~22 MB/s, "
            f"~89 ms RTT cap it near ~{22e6 / bps / 1e3:.0f}k sigs/s at "
            f"the measured {bps:.0f} B/sig); device_sigs_per_s is the "
            f"chip-bound co-headline")

    # -- subsystem benches (each guarded: a failure reports, not aborts)
    for fn in (bench_blocksync, bench_mixed_megacommit, bench_attribution,
               bench_challenge,
               bench_light_client, bench_light_fleet, bench_bls,
               bench_cert, bench_consensus_tpu, bench_scheduler, bench_storage,
               bench_soak, bench_mesh, bench_fleet):
        try:
            _progress(fn.__name__)
            fn(detail)
        except Exception as e:  # noqa: BLE001
            detail[fn.__name__] = f"FAILED: {type(e).__name__}: {e}"

    # HEADLINE: device-bound throughput (rep-differenced, repeatable to a
    # few % across runs). The wire-bound stream number collapses under
    # dev-box tunnel contention (r3: 55.8k, a contended rerun: 15.5k for
    # the SAME kernel) and is kept in detail with the cap stated.
    headline = device_sigs_per_s if device_sigs_per_s else tpu_sigs_per_s
    record = {
        "metric": "ed25519_verify_throughput",
        "value": round(headline, 1),
        "unit": "sigs/sec/chip (device-bound)",
        "vs_baseline": round(headline / cpu_batch_pinned, 2),
        "detail": detail,
    }
    print(json.dumps(record))
    return record


def _write_out(record: dict, path: str) -> None:
    """Write the FULL bench record to a file, atomically (tmp + rename):
    the driver captures stdout with a bounded tail, which truncated
    BENCH_r05 into a `"parsed": null` round — the out-file is the
    untruncatable copy. tools/bench_compare.load_snapshot auto-discovers
    `<snapshot stem>.out.json` next to a driver snapshot and prefers it."""
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(record, f)
        f.write("\n")
    os.replace(tmp, path)
    print(f"[bench] full record written to {path}", file=sys.stderr,
          flush=True)


def _cli() -> int:
    """Plain `python bench.py` prints the one headline JSON line (the
    driver contract, unchanged). `--out FILE` additionally writes the
    full record to FILE so stdout truncation can never lose a round.
    `--compare BENCH_rNN.json` additionally runs the regression sentinel
    (tools/bench_compare.py) against the prior snapshot and prints its
    machine-readable verdict as a second line — exit 1 when a tracked
    metric regressed past its threshold. `--current saved.json` skips
    the run and diffs two files."""
    import argparse

    p = argparse.ArgumentParser(prog="bench.py")
    p.add_argument("--out", default="",
                   help="also write the full JSON record to this file "
                        "(atomic; name it <snapshot stem>.out.json and "
                        "bench_compare auto-discovers it)")
    p.add_argument("--compare", default="",
                   help="prior snapshot (BENCH_rNN.json or a saved bench "
                        "line) to diff this run against")
    p.add_argument("--current", default="",
                   help="with --compare: diff this saved run instead of "
                        "running the bench")
    p.add_argument("--mesh", action="store_true",
                   help="run ONLY the multi-chip mesh scenario (subprocess "
                        "on forced host devices) and print its record")
    p.add_argument("--fleet", action="store_true",
                   help="run ONLY the fleet-size-curve scenario (OS-process "
                        "testnets at BENCH_FLEET_SIZES) and print its record")
    p.add_argument("--soak", action="store_true",
                   help="run ONLY the saturation soak (overload plane): "
                        "4-val in-proc net under 2x-ceiling admission "
                        "waves; emits soak_heights_per_s, "
                        "admission_txs_per_s, height_p99_under_load_ms")
    p.add_argument("--discovery", action="store_true",
                   help="run ONLY the discovery-plane scenario: an organic "
                        "fleet bootstrapping from one seed via PEX "
                        "(bootstrap_convergence_s) + a sybil flood against "
                        "the hashed-bucket address book "
                        "(eclipse_book_occupancy_pct)")
    p.add_argument("--mesh-child", action="store_true",
                   help="internal: the in-process mesh scenario (must run "
                        "under JAX_PLATFORMS=cpu with forced host devices)")
    args = p.parse_args()
    if args.mesh_child:
        record = mesh_child_main()
        if args.out:
            _write_out(record, args.out)
        return 0
    if args.mesh:
        record = run_mesh_bench(int(os.environ.get("BENCH_MESH_DEVICES", "8")))
        print(json.dumps(record))
        if args.out:
            _write_out(record, args.out)
        return 0
    if args.soak:
        detail: dict = {}
        bench_soak(detail)
        # no top-level "value": the headline here, height_p99_under_load_ms,
        # is LOWER-better and lives under its own TRACKED name
        record = {"metric": "overload_soak",
                  "value": None,
                  "unit": "see detail.height_p99_under_load_ms (lower is "
                          "better) + soak_heights_per_s/admission_txs_per_s",
                  "detail": detail}
        print(json.dumps(record))
        if args.out:
            _write_out(record, args.out)
        return 0
    if args.discovery:
        detail: dict = {}
        bench_discovery(detail)
        # no top-level "value": the headline, bootstrap_convergence_s,
        # is LOWER-better and lives under its own TRACKED name;
        # eclipse occupancy is a bound check, informational
        record = {"metric": "discovery_plane",
                  "value": None,
                  "unit": "see detail.bootstrap_convergence_s (lower is "
                          "better) + eclipse_book_occupancy_pct",
                  "detail": detail}
        print(json.dumps(record))
        if args.out:
            _write_out(record, args.out)
        return 0
    if args.fleet:
        detail: dict = {}
        bench_fleet(detail)
        # no top-level "value": the sentinel's generic value entry is
        # higher-better (the main bench's sigs/s headline) — this
        # record's headline, amplification, is LOWER-better and lives
        # under its own correctly-directioned TRACKED name
        record = {"metric": "fleet_testnet_curves",
                  "value": None,
                  "unit": "see detail.gossip_votes_per_vote_needed "
                          "(amplification; lower is better) + fleet curve",
                  "detail": detail}
        print(json.dumps(record))
        if args.out:
            _write_out(record, args.out)
        return 0
    if not args.compare:
        record = main()
        if args.out:
            _write_out(record, args.out)
        return 0
    from tools import bench_compare

    if args.current:
        record = bench_compare.load_snapshot(args.current)
    else:
        record = main()
        if args.out:
            _write_out(record, args.out)
    verdict = bench_compare.compare(
        bench_compare.load_snapshot(args.compare), record)
    print(json.dumps(verdict))
    return 0 if verdict["verdict"] == "pass" else 1


if __name__ == "__main__":
    sys.exit(_cli())
